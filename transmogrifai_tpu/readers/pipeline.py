"""Async sharded input pipeline: shard → interleave → map → prefetch.

The tf.data design (PAPERS.md: arXiv 2101.12127) applied to this
engine's readers: ingest used to be phase-serial — parse, then
transform, then fit, each waiting on the last — so end-to-end CSV
ingest ran well below what the parser alone sustains (BENCH_r05:
611k rows/s end-to-end vs 838k parse-only), and the fit tier idled
through the whole parse.  This module is the pipelined counterpart
(reference: the Spark-partition parallel ingest the source system got
from DataReaders.scala for free, rebuilt TPU-native):

* :func:`shard` — declare the work-list: one :class:`ShardSpec` per
  file (CSV / Parquet / Avro by extension), shard ids in the given
  (deterministic) order.
* **parallel interleave** — N worker threads pull shards off a work
  queue and parse them concurrently.  The native CSV scanner releases
  the GIL for the scan (ctypes) and its per-call thread fan-out is
  capped via ``TX_CSV_THREADS`` while the pipeline runs, so W workers
  do not oversubscribe the host.
* **map** — decode + quarantine runs inside the worker (the per-chunk
  ``transform=`` hook), so per-chunk python work is interleaved too.
* **prefetch** — decoded chunks flow through ONE bounded queue
  (``buffer_chunks`` deep) with backpressure: a full buffer blocks
  producers (counted as producer stall), an empty one blocks the
  consumer (consumer stall).  Every blocking wait in this module is
  bounded (the tests/test_style.py pipeline gate) so a crashed peer
  can never wedge ingest forever.
* **consumer** — downstream work (feature materialization, vectorizer
  fitting, CV fold construction) starts on the FIRST ready chunk
  instead of the last; ``ordered=True`` optionally reassembles source
  order on the fly via the (shard_id, chunk_id) pair every chunk
  carries.

Failure semantics: a worker exception is wrapped as
:class:`ShardIngestError` naming the shard id and file, forwarded
through the queue, and re-raised in the consumer; the pipeline then
stops all workers and drains the queue — no hang, no silent partial
dataset.  Per-shard :class:`~..schema.quarantine.QuarantineBuffer`\\ s
merge into exact global counts with stable global row indices,
deterministic regardless of shard completion order
(:meth:`InputPipeline.merged_quarantine`).

Observability (obs/): each shard parse is an ``ingest.shard`` span
parented to the ambient run trace (worker threads inherit the caller's
context), and the registry carries ``pipeline.buffer_depth`` /
``pipeline.producer_stall_ms`` / ``pipeline.consumer_stall_ms`` /
``pipeline.chunks`` series so the overlap is visible, not inferred.
"""
from __future__ import annotations

import contextvars
import csv as _csv
import heapq
import os
import queue
import threading
import time
import weakref
from typing import Any, Callable, Mapping, Optional, Sequence, Type

import numpy as np

from ..obs import trace as _obs_trace
from ..obs.metrics import metrics_registry
from ..schema.quarantine import (
    QuarantineBuffer,
    check_errors_mode,
    coerce_numeric,
    data_telemetry,
    excerpt_of,
    MalformedRowError,
)
from ..types.feature_types import FeatureType, OPNumeric
from .fast_csv import (
    CsvChunk,
    assemble_columns,
    chunk_to_block,
    fast_path_available,
    iter_csv_chunks,
)

DEFAULT_WORKERS = 4
DEFAULT_BUFFER_CHUNKS = 8
#: pipeline chunks are smaller than fast_csv's 64 MB serial default:
#: interleave needs several chunks in flight per shard for overlap
DEFAULT_CHUNK_BYTES = 16 << 20
DEFAULT_CHUNK_ROWS = 200_000  # record-oriented shards (avro)
#: bounded-wait quantum: every queue put/get blocks at most this long
#: before re-checking the stop flag (no unbounded blocking — gate-pinned)
POLL_S = 0.05
_JOIN_S = 30.0

_FMT_BY_EXT = {
    ".csv": "csv", ".parquet": "parquet", ".pq": "parquet",
    ".avro": "avro",
}


class ShardSpec:
    """One unit of the interleave work-list: a file plus its position
    in the deterministic source order."""

    __slots__ = ("shard_id", "path", "fmt")

    def __init__(self, shard_id: int, path: str,
                 fmt: Optional[str] = None) -> None:
        self.shard_id = int(shard_id)
        self.path = str(path)
        if fmt is None:
            fmt = _FMT_BY_EXT.get(os.path.splitext(path)[1].lower(), "csv")
        self.fmt = fmt

    def __repr__(self) -> str:
        return f"ShardSpec({self.shard_id}, {self.path!r}, {self.fmt!r})"


def shard(paths: Sequence[str], fmt: Optional[str] = None) -> list[ShardSpec]:
    """Build the shard list from file paths.  Order is the caller's
    order (shard ids are positional) — callers that need a canonical
    order sort first; the pipeline's global row indices and ordered
    reassembly both key off these ids."""
    return [ShardSpec(i, p, fmt) for i, p in enumerate(paths)]


class ShardDirectoryFollower:
    """Follow/tail mode for the sharded pipeline (ISSUE 16): watch a
    directory and hand out shard files that arrive AFTER start, as
    :class:`ShardSpec`\\ s whose ids keep growing monotonically across
    polls — so global row indices, quarantine attribution and ordered
    reassembly stay stable over the whole lifetime of a long-lived
    consumer (the continuous trainer), exactly as if the shards had all
    been declared up front via :func:`shard`.

    Pick-up contract: a file is eligible the first poll it exists with
    a recognized shard extension (``_FMT_BY_EXT``, or any extension
    when ``fmt=`` pins the format).  Producers must therefore publish
    shards ATOMICALLY — write to a temp name and ``os.replace`` into
    the watched directory (``testkit.drills.write_shard_csv`` is the
    reference writer) — or set ``settle_s`` so a file is only taken
    once its mtime is at least that old.  Files arriving within one
    poll are ordered lexicographically by name; each file is consumed
    exactly once, keyed by name (a shard overwritten in place is NOT
    re-read — publish a new name instead)."""

    def __init__(self, directory: str, fmt: Optional[str] = None,
                 settle_s: float = 0.0) -> None:
        self.directory = str(directory)
        self.fmt = fmt
        self.settle_s = float(settle_s)
        self._seen: set = set()
        self._next_id = 0

    @property
    def shards_seen(self) -> int:
        """How many shards have been handed out so far."""
        return self._next_id

    def poll(self) -> list[ShardSpec]:
        """New shards since the last poll (possibly empty; never
        blocks).  A missing watch directory is 'nothing new yet', not
        an error — the producer may not have created it."""
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return []
        specs: list[ShardSpec] = []
        now = time.time()
        for name in names:
            if name in self._seen:
                continue
            if self.fmt is None:
                ext = os.path.splitext(name)[1].lower()
                if ext not in _FMT_BY_EXT:
                    continue
            path = os.path.join(self.directory, name)
            if not os.path.isfile(path):
                continue
            if self.settle_s > 0:
                try:
                    settling = (now - os.stat(path).st_mtime
                                < self.settle_s)
                except OSError:
                    settling = True  # vanished mid-poll: re-decide later
                if settling:
                    continue  # still settling: next poll's problem
            self._seen.add(name)
            specs.append(ShardSpec(self._next_id, path, self.fmt))
            self._next_id += 1
        return specs

    def pipeline(self, specs: Sequence[ShardSpec],
                 schema: Mapping[str, Type[FeatureType]],
                 **kw: Any) -> Optional["InputPipeline"]:
        """One single-use :class:`InputPipeline` over one poll's shards
        (None when the poll was empty) — the tail consumer's per-window
        ingest rides the exact same interleave/prefetch machinery as a
        batch read."""
        if not specs:
            return None
        return InputPipeline(list(specs), schema, **kw)


class ShardIngestError(RuntimeError):
    """A worker failed parsing one shard; names the shard and file so
    the operator knows exactly which input to look at."""

    def __init__(self, shard_id: int, path: str,
                 cause: BaseException) -> None:
        self.shard_id = shard_id
        self.path = path
        self.cause = cause
        super().__init__(
            f"shard {shard_id} ({path}): ingest failed: "
            f"{type(cause).__name__}: {cause}"
        )


class PipelineChunk:
    """Envelope the prefetch queue carries: the (shard_id, chunk_id)
    determinism seam plus the decoded payload (a fast_csv.CsvChunk, or
    whatever the worker-side ``transform=`` returned)."""

    __slots__ = ("shard_id", "chunk_id", "n_rows", "payload")

    def __init__(self, shard_id: int, chunk_id: int, n_rows: int,
                 payload: Any) -> None:
        self.shard_id = shard_id
        self.chunk_id = chunk_id
        self.n_rows = n_rows
        self.payload = payload

    @property
    def order_key(self) -> tuple[int, int]:
        return (self.shard_id, self.chunk_id)


class PipelineStats:
    """Where the wall time went: producer busy/stall, consumer stall,
    and the overlap fraction the bench and the tier-1 floor read.
    ``overlap_fraction`` is the share of total producer busy time that
    ran while OTHER work (another producer or the consumer) was also
    running — 0 on a serial pipeline, approaching (W-1)/W on a
    perfectly interleaved one."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.wall_s = 0.0
        self.chunks = 0
        self.rows = 0
        self.producer_busy_s = 0.0
        self.producer_stall_s = 0.0
        self.consumer_stall_s = 0.0
        self.shards: dict[int, dict] = {}
        #: the knob settings this pipeline ran under (stamped by
        #: InputPipeline) so one snapshot carries signal + knobs for
        #: the autotune proposer (autotune/knobs.py)
        self.knobs: dict = {}

    def _add(self, **kw: float) -> None:
        with self._lock:
            for k, v in kw.items():
                setattr(self, k, getattr(self, k) + v)

    def record_stall(self, side: str, seconds: float) -> None:
        """One bounded-wait quantum spent blocked on the prefetch
        buffer (side = 'producer' on a full buffer, 'consumer' on an
        empty one) — the backpressure accounting the bench and the
        overlap telemetry read."""
        if side == "producer":
            self._add(producer_stall_s=seconds)
        else:
            self._add(consumer_stall_s=seconds)

    def _shard_done(self, shard_id: int, info: dict) -> None:
        with self._lock:
            self.shards[shard_id] = info

    @property
    def overlap_fraction(self) -> float:
        if self.wall_s <= 0 or self.producer_busy_s <= 0:
            return 0.0
        # busy time beyond one serial lane's worth of wall is provably
        # concurrent work
        return max(0.0, min(
            1.0, 1.0 - self.wall_s / self.producer_busy_s
        )) if self.producer_busy_s > self.wall_s else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "wall_s": round(self.wall_s, 4),
                "chunks": self.chunks,
                "rows": self.rows,
                "producer_busy_s": round(self.producer_busy_s, 4),
                "producer_stall_s": round(self.producer_stall_s, 4),
                "consumer_stall_s": round(self.consumer_stall_s, 4),
                "overlap_fraction": round(self.overlap_fraction, 4),
                "knobs": dict(self.knobs),
                "shards": {k: dict(v) for k, v in self.shards.items()},
            }


# ---------------------------------------------------------------------------
# per-format shard chunk iterators (the map stage's decode half)
# ---------------------------------------------------------------------------
def _iter_csv_chunks_python(
    path: str,
    schema: Mapping[str, Type[FeatureType]],
    wanted: Sequence[str],
    chunk_rows: int,
    errors: str,
    quarantine: Optional[QuarantineBuffer],
    telemetry,
):
    """Pure-python CSV shard fallback (no native lib): same chunk
    contract and the same junk rule (schema.quarantine.coerce_numeric)
    as the native iterator, including ragged-row detection that the
    native scanner cannot do."""
    checked = errors != "coerce"
    if checked and quarantine is None:
        quarantine = QuarantineBuffer(source=path)
    with open(path, newline="", encoding="utf-8-sig") as f:
        reader = _csv.reader(f)
        header = next(reader, None)
        if header is None:
            return
        missing = [n for n in wanted if n not in header]
        if missing:
            raise KeyError(f"columns {missing} not in CSV {path}")
        col_idx = {n: header.index(n) for n in wanted}
        numeric = [n for n in wanted if issubclass(schema[n], OPNumeric)]
        ncols = len(header)
        rows_seen = rows_kept = 0
        buf_rows: list[list] = []
        chunk_start = 0

        def flush():
            num: dict[str, tuple[np.ndarray, np.ndarray]] = {}
            text: dict[str, np.ndarray] = {}
            for n in wanted:
                c = col_idx[n]
                cells = [r[c] if c < len(r) else "" for r in buf_rows]
                if n in numeric:
                    vals = np.empty(len(cells))
                    mask = np.zeros(len(cells), bool)
                    for i, cell in enumerate(cells):
                        v = coerce_numeric(cell) if cell else None
                        if v is None or v != v:
                            vals[i] = 0.0
                        else:
                            vals[i] = v
                            mask[i] = True
                    num[n] = (vals, mask)
                else:
                    out = np.empty(len(cells), dtype=object)
                    for i, cell in enumerate(cells):
                        out[i] = cell if cell else None
                    text[n] = out
            return CsvChunk(len(buf_rows), num, text, chunk_start)

        from ..faults import injection as _faults

        for i, r in enumerate(reader):
            bad_reason = bad_col = bad_cell = None
            if checked:
                # same drill points as the native and per-file readers:
                # a host without the native lib must still exercise the
                # real detection machinery under fault injection
                if _faults.fires("reader.malformed_row") is not None:
                    r = r[: max(len(r) - 1, 0)]
                if numeric and _faults.fires(
                        "reader.type_flip") is not None:
                    r = list(r)
                    c0 = col_idx[numeric[0]]
                    if c0 < len(r):
                        r[c0] = "\x00<injected-junk>"
                if len(r) != ncols:
                    bad_reason = ("truncated_row" if len(r) < ncols
                                  else "extra_fields")
                    bad_cell = ",".join(r)
                else:
                    for n in numeric:
                        cell = r[col_idx[n]]
                        if cell and coerce_numeric(cell) is None:
                            bad_reason, bad_col, bad_cell = (
                                "type_flip", n, cell)
                            break
            rows_seen += 1
            if bad_reason is not None:
                if errors == "strict":
                    (telemetry or data_telemetry()).record_strict_error(
                        path)
                    raise MalformedRowError(
                        path, i, bad_reason, bad_col, excerpt_of(bad_cell))
                quarantine.add(i, bad_reason, bad_col,
                               excerpt_of(bad_cell))
                continue
            rows_kept += 1
            buf_rows.append(r)
            if len(buf_rows) >= chunk_rows:
                yield flush()
                buf_rows = []
                chunk_start = i + 1
        if buf_rows:
            yield flush()
    if checked:
        (telemetry or data_telemetry()).record_read(
            path, rows_seen, rows_kept, quarantine)


def _iter_parquet_chunks(
    path: str,
    schema: Mapping[str, Type[FeatureType]],
    wanted: Sequence[str],
    chunk_rows: int,
    errors: str,
    quarantine: Optional[QuarantineBuffer],
    telemetry,
):
    """Parquet shard -> CsvChunk stream via Arrow record batches,
    sharing the checked block converters with DeviceParquetIngest."""
    import pyarrow.parquet as pq

    from .arrow_ingest import (
        batch_to_numeric_block,
        checked_batch_to_numeric_block,
    )

    checked = errors != "coerce"
    if checked and quarantine is None:
        quarantine = QuarantineBuffer(source=path)
    num_names = [n for n in wanted if issubclass(schema[n], OPNumeric)]
    text_names = [n for n in wanted if n not in num_names]
    if checked and text_names:
        raise TypeError(
            "parquet checked modes with text columns are not supported "
            "on the pipelined path; use ParquetReader"
        )
    pf = pq.ParquetFile(path)
    rows_seen = rows_kept = 0
    for batch in pf.iter_batches(batch_size=chunk_rows,
                                 columns=list(wanted)):
        n = batch.num_rows
        if n == 0:
            continue
        row_offset = rows_seen
        if checked and num_names:
            vals, mask, n_bad = checked_batch_to_numeric_block(
                batch, num_names, errors, quarantine, rows_seen, path,
                telemetry=telemetry,
            )
        elif num_names:
            vals, mask = batch_to_numeric_block(batch, num_names)
            n_bad = 0
        else:
            vals = np.zeros((n, 0), np.float32)
            mask = np.zeros((n, 0), bool)
            n_bad = 0
        rows_seen += n
        rows_kept += n - n_bad
        num = {
            nm: (np.asarray(vals[:, j], dtype=np.float64), mask[:, j])
            for j, nm in enumerate(num_names)
        }
        text: dict[str, np.ndarray] = {}
        for nm in text_names:
            col = np.empty(n, dtype=object)
            for i, v in enumerate(batch.column(nm).to_pylist()):
                col[i] = None if v in (None, "") else str(v)
            text[nm] = col
        yield CsvChunk(n - n_bad, num, text, row_offset)
    if checked:
        (telemetry or data_telemetry()).record_read(
            path, rows_seen, rows_kept, quarantine)


def _iter_avro_chunks(
    path: str,
    schema: Mapping[str, Type[FeatureType]],
    wanted: Sequence[str],
    chunk_rows: int,
    errors: str,
    quarantine: Optional[QuarantineBuffer],
    telemetry,
):
    """Avro shard -> CsvChunk stream: OCF blocks decode incrementally
    through :class:`~.avro_reader.AvroBlockStream` (which owns
    corrupt-block quarantine and sync-marker resync), then buffer into
    columnar slices of ``chunk_rows`` — an avro shard now streams truly
    chunk-by-chunk like CSV and Parquet, never materializing the whole
    record list."""
    from ..faults import injection as _faults
    from .avro_reader import AvroBlockStream

    checked = errors != "coerce"
    if checked and quarantine is None:
        quarantine = QuarantineBuffer(source=path)
    num_names = [n for n in wanted if issubclass(schema[n], OPNumeric)]
    rows_kept = 0

    def _columnar(chunk: list, start: int) -> CsvChunk:
        nonlocal rows_kept
        keep = np.ones(len(chunk), bool)
        if checked:
            # same per-record junk rule as AvroReader._checked_records:
            # a non-None numeric value that refuses coerce_numeric is a
            # type flip (strict raises, quarantine drops the record) -
            # the pipelined route must count exactly like the serial one
            for i, r in enumerate(chunk):
                bad_reason = bad_col = bad_cell = None
                if not isinstance(r, Mapping):
                    bad_reason, bad_cell = "malformed_record", r
                else:
                    for n in num_names:
                        v = r.get(n)
                        if v is not None and coerce_numeric(v) is None:
                            bad_reason, bad_col, bad_cell = (
                                "type_flip", n, v)
                            break
                if bad_reason is None and _faults.fires(
                        "reader.type_flip") is not None:
                    bad_reason, bad_col, bad_cell = (
                        "type_flip", num_names[0] if num_names else None,
                        "<injected>")
                if bad_reason is None and _faults.fires(
                        "reader.malformed_row") is not None:
                    bad_reason, bad_cell = "malformed_record", "<injected>"
                if bad_reason is None:
                    continue
                if errors == "strict":
                    (telemetry or data_telemetry()).record_strict_error(
                        path)
                    raise MalformedRowError(
                        path, start + i, bad_reason, bad_col,
                        excerpt_of(bad_cell))
                quarantine.add(start + i, bad_reason, bad_col,
                               excerpt_of(bad_cell))
                keep[i] = False
            if not keep.all():
                chunk = [r for r, k in zip(chunk, keep) if k]
        rows_kept += len(chunk)
        num: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        text: dict[str, np.ndarray] = {}
        for n in wanted:
            if n in num_names:
                vals = np.zeros(len(chunk))
                mask = np.zeros(len(chunk), bool)
                for i, r in enumerate(chunk):
                    v = r.get(n)
                    v = None if v is None else coerce_numeric(v)
                    if v is not None and v == v:
                        vals[i] = v
                        mask[i] = True
                num[n] = (vals, mask)
            else:
                out = np.empty(len(chunk), dtype=object)
                for i, r in enumerate(chunk):
                    v = r.get(n)
                    out[i] = None if v in (None, "") else str(v)
                text[n] = out
        return CsvChunk(len(chunk), num, text, start)

    stream = AvroBlockStream(path, errors=errors, quarantine=quarantine)
    try:
        # chunk boundaries, quarantine record indexes, and rows_seen
        # must match the old materialize-then-slice path exactly:
        # `start` counts positions in the cleanly decoded record stream
        # (damaged blocks contribute nothing - the stream rolls them
        # back), so every slice is bit-identical to records[start:
        # start+chunk_rows] of a full decode
        pending: list = []
        start = 0
        for block in stream.blocks():
            pending.extend(block)
            while len(pending) >= chunk_rows:
                chunk, pending = (pending[:chunk_rows],
                                  pending[chunk_rows:])
                yield _columnar(chunk, start)
                start += chunk_rows
        if pending:
            yield _columnar(pending, start)
    finally:
        stream.close()
    if checked:
        (telemetry or data_telemetry()).record_read(
            path, stream.records_decoded + stream.damaged, rows_kept,
            quarantine)


def iter_shard_chunks(
    spec: ShardSpec,
    schema: Mapping[str, Type[FeatureType]],
    wanted: Sequence[str],
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    chunk_rows: int = DEFAULT_CHUNK_ROWS,
    errors: str = "coerce",
    quarantine: Optional[QuarantineBuffer] = None,
    telemetry=None,
    use_native: bool = True,
):
    """Format dispatch for one shard: CSV rides the native chunk scanner
    (python fallback when unavailable), Parquet rides Arrow record
    batches, Avro decodes records then slices columnar."""
    if spec.fmt == "csv":
        if use_native and fast_path_available():
            return iter_csv_chunks(
                spec.path, schema, chunk_bytes=chunk_bytes,
                wanted=wanted, errors=errors, quarantine=quarantine,
                telemetry=telemetry,
            )
        return _iter_csv_chunks_python(
            spec.path, schema, wanted, chunk_rows, errors, quarantine,
            telemetry,
        )
    if spec.fmt == "parquet":
        return _iter_parquet_chunks(
            spec.path, schema, wanted, chunk_rows, errors, quarantine,
            telemetry,
        )
    if spec.fmt == "avro":
        return _iter_avro_chunks(
            spec.path, schema, wanted, chunk_rows, errors, quarantine,
            telemetry,
        )
    raise ValueError(f"unknown shard format {spec.fmt!r} for {spec.path}")


# ---------------------------------------------------------------------------
# the pipeline
# ---------------------------------------------------------------------------
#: weakref to the most recently constructed InputPipeline: the shared
#: ``pipeline.buffer_depth`` pull gauge reads through it so the metric
#: follows the live pipeline without the registry retaining any queue
_depth_source: Optional["weakref.ref"] = None


def _current_buffer_depth() -> float:
    pipe = _depth_source() if _depth_source is not None else None
    return float(pipe._queue.qsize()) if pipe is not None else 0.0


#: refcounted native-scan fan-out cap: concurrent pipelines in one
#: process share it — the FIRST to start installs it, the LAST to finish
#: clears it.  The cap rides an ATOMIC inside the native lib
#: (utils.native.set_csv_threads), never an os.environ mutation: glibc
#: setenv/unsetenv while another thread's scan reads getenv is
#: use-after-free UB.  An operator-set TX_CSV_THREADS env var (static,
#: never mutated — safe to read) wins over the dynamic cap being absent.
_cap_lock = threading.Lock()
_cap_active = 0


def _acquire_thread_cap(workers: int) -> bool:
    """Cap the native scanner's per-call fan-out while multi-worker
    pipelines run; returns True when this caller must later release."""
    if workers <= 1 or os.environ.get("TX_CSV_THREADS"):
        return False  # single lane, or the operator pinned a static cap
    from ..utils import native

    global _cap_active
    with _cap_lock:
        if _cap_active == 0:
            if not native.set_csv_threads(
                    max(1, (os.cpu_count() or 8) // workers)):
                return False  # no native lib: nothing to cap
        _cap_active += 1
        return True


def _release_thread_cap() -> None:
    from ..utils import native

    global _cap_active
    with _cap_lock:
        _cap_active -= 1
        if _cap_active == 0:
            native.set_csv_threads(0)


class InputPipeline:
    """shard → interleave(workers) → map(decode/quarantine/transform) →
    prefetch(bounded buffer) → consumer.

    ``transform=`` runs inside the worker on each decoded CsvChunk (the
    map stage's caller half — e.g. ``chunk_to_block`` for design-matrix
    consumers) so its cost interleaves too.  ``ordered=True`` makes
    :meth:`chunks` yield in exact (shard_id, chunk_id) source order —
    parsing stays parallel; only the hand-off reorders.  The reorder
    heap must keep draining the prefetch queue while it waits for the
    next-due chunk (stopping would deadlock against the shard still
    producing it), so under pathological shard-size skew it can grow
    toward the later shards' decoded size; consumers that only need
    DETERMINISM, not streaming order, should prefer the
    sort-at-assembly helpers (``pipelined_columns`` /
    ``pipelined_design_matrix``), which hold the same data without the
    heap bookkeeping.
    """

    def __init__(
        self,
        shards: Sequence[ShardSpec],
        schema: Mapping[str, Type[FeatureType]],
        wanted: Optional[Sequence[str]] = None,
        workers: int = DEFAULT_WORKERS,
        buffer_chunks: int = DEFAULT_BUFFER_CHUNKS,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        chunk_rows: int = DEFAULT_CHUNK_ROWS,
        errors: str = "coerce",
        ordered: bool = False,
        transform: Optional[Callable[[CsvChunk], Any]] = None,
        telemetry=None,
        use_native: bool = True,
        quarantine_max_rows: Optional[int] = None,
    ) -> None:
        if not shards:
            raise ValueError("input pipeline needs at least one shard")
        if workers < 1:
            raise ValueError("workers must be >= 1")
        if buffer_chunks < 1:
            raise ValueError("buffer_chunks must be >= 1")
        self.shards = list(shards)
        self.schema = dict(schema)
        self.wanted = [n for n in (wanted or list(schema)) if n in schema]
        self.workers = int(min(workers, len(self.shards)))
        self.buffer_chunks = int(buffer_chunks)
        self.chunk_bytes = int(chunk_bytes)
        self.chunk_rows = int(chunk_rows)
        self.errors = check_errors_mode(errors)
        self.ordered = bool(ordered)
        self.transform = transform
        self.telemetry = telemetry
        self.use_native = use_native
        self.quarantine_max_rows = quarantine_max_rows
        self.stats = PipelineStats()
        self.stats.knobs = {
            "workers": self.workers,
            "buffer_chunks": self.buffer_chunks,
        }
        self.shard_quarantines: dict[int, QuarantineBuffer] = {}
        self._shard_rows_seen: dict[int, int] = {}
        self._stop = threading.Event()
        # raised by the FIRST failing worker (after its error item is
        # queued): peers stop pulling shards and abandon their current
        # one at the next chunk boundary instead of parsing work the
        # consumer is about to throw away
        self._failed = threading.Event()
        self._queue: queue.Queue = queue.Queue(maxsize=self.buffer_chunks)
        self._threads: list[threading.Thread] = []
        self._consumed = False
        reg = metrics_registry()
        # the depth gauge tracks the MOST RECENT live pipeline through a
        # module-level weakref: get-or-create would otherwise freeze the
        # pull fn on the first pipeline's (long-drained) queue and pin
        # that queue alive in the registry forever
        global _depth_source
        _depth_source = weakref.ref(self)
        self._m_depth = reg.gauge(
            "pipeline.buffer_depth",
            help="prefetch queue depth (chunks) of the most recent "
                 "pipeline", fn=_current_buffer_depth,
        )
        self._m_prod_stall = reg.counter(
            "pipeline.producer_stall_ms",
            help="time producers blocked on a full prefetch buffer",
        )
        self._m_cons_stall = reg.counter(
            "pipeline.consumer_stall_ms",
            help="time the consumer blocked on an empty prefetch buffer",
        )
        self._m_chunks = reg.counter(
            "pipeline.chunks", help="chunks delivered to the consumer",
        )
        # knob visibility (ISSUE 13): the live worker/buffer settings
        # next to the stall counters they explain, so the autotune
        # pipeline proposer (autotune/knobs.propose_pipeline_knobs) and
        # a Prometheus scrape both see knob + signal in one place
        reg.gauge(
            "pipeline.workers",
            help="parser worker threads of the most recent pipeline",
        ).set(float(self.workers))
        reg.gauge(
            "pipeline.buffer_chunks",
            help="prefetch buffer capacity (chunks) of the most recent "
                 "pipeline",
        ).set(float(self.buffer_chunks))

    # -- producer side -------------------------------------------------------
    def _put(self, item) -> bool:
        """Bounded-wait put with backpressure accounting (one POLL_S
        quantum per blocked wait); returns False when the pipeline was
        stopped before the item fit."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=POLL_S)
                return True
            except queue.Full:
                # backpressure: the full buffer is ACCOUNTED, not
                # swallowed - stall time drives the overlap telemetry
                self.stats.record_stall("producer", POLL_S)
                self._m_prod_stall.inc(POLL_S * 1e3)
        return False

    def _worker(self, work: "queue.Queue[ShardSpec]") -> None:
        try:
            while not (self._stop.is_set() or self._failed.is_set()):
                try:
                    spec = work.get(timeout=0.0)
                except queue.Empty:
                    break
                self._run_shard(spec)
        finally:
            with self.stats._lock:
                self._live -= 1
                last = self._live == 0
            if last:
                self._put(("done", None))

    def _run_shard(self, spec: ShardSpec) -> None:
        if self.quarantine_max_rows is not None:
            buf = QuarantineBuffer(max_rows=self.quarantine_max_rows,
                                   source=spec.path)
        else:
            buf = QuarantineBuffer(source=spec.path)
        self.shard_quarantines[spec.shard_id] = buf
        t0 = time.perf_counter()
        chunk_id = 0
        rows = 0
        try:
            with _obs_trace.span(
                "ingest.shard", shard=spec.shard_id, source=spec.path,
                format=spec.fmt, errors=self.errors,
            ) as sp:
                for chunk in iter_shard_chunks(
                    spec, self.schema, self.wanted,
                    chunk_bytes=self.chunk_bytes,
                    chunk_rows=self.chunk_rows, errors=self.errors,
                    quarantine=buf, telemetry=self.telemetry,
                    use_native=self.use_native,
                ):
                    payload = (self.transform(chunk) if self.transform
                               else chunk)
                    rows += chunk.n_rows
                    # busy windows CLOSE before the put and REOPEN after
                    # it returns: time blocked on a full buffer is stall,
                    # never busy - double-counting it would let
                    # overlap_fraction read high on a pipeline with zero
                    # real overlap (and fake out the tier-1 floor gate)
                    self.stats._add(
                        producer_busy_s=time.perf_counter() - t0)
                    ok = self._put(("chunk", PipelineChunk(
                        spec.shard_id, chunk_id, chunk.n_rows, payload,
                    )))
                    t0 = time.perf_counter()
                    if not ok or self._failed.is_set():
                        return
                    chunk_id += 1
                    # progress is recorded INCREMENTALLY so a shard that
                    # never completes (peer failure, abandoned consumer)
                    # still contributes its produced rows to the merged
                    # quarantine's global row offsets
                    self._shard_rows_seen[spec.shard_id] = (
                        rows + buf.total)
                sp.set_attr("rows", rows)
                sp.set_attr("chunks", chunk_id)
                sp.set_attr("quarantined", buf.total)
        except BaseException as e:  # forwarded: consumer re-raises
            self.stats._add(producer_busy_s=time.perf_counter() - t0)
            self._put(("error", ShardIngestError(
                spec.shard_id, spec.path, e)))
            # flag AFTER the error item is queued (a pre-put flag would
            # stop our own bounded put): peers wind down without parsing
            # shards the consumer is about to discard
            self._failed.set()
            return
        self.stats._add(producer_busy_s=time.perf_counter() - t0)
        self._shard_rows_seen[spec.shard_id] = rows + buf.total
        self.stats._shard_done(spec.shard_id, {
            "path": spec.path, "chunks": chunk_id, "rows_kept": rows,
            "quarantined": buf.total,
        })
        self._put(("shard_done", (spec.shard_id, chunk_id)))

    # -- consumer side -------------------------------------------------------
    def chunks(self):
        """Yield :class:`PipelineChunk`\\ s as workers land them (or in
        exact source order with ``ordered=True``).  Re-raises
        :class:`ShardIngestError` on any worker failure after stopping
        the fleet; always leaves the pipeline drained and the workers
        joined, even when the consumer abandons iteration early."""
        if self._consumed:
            raise RuntimeError("InputPipeline.chunks() is single-use; "
                               "build a new pipeline per pass")
        self._consumed = True
        work: queue.Queue = queue.Queue()
        for spec in self.shards:
            work.put(spec, timeout=POLL_S)
        self._live = self.workers
        t_start = time.perf_counter()
        # cap the native scanner's internal fan-out while several shard
        # scans run concurrently (refcounted: safe under concurrent
        # pipelines, restored when the last one finishes)
        owns_cap = _acquire_thread_cap(self.workers)
        # worker threads inherit the caller's contextvars so their
        # ingest.shard spans parent into the ambient run trace
        for i in range(self.workers):
            ctx = contextvars.copy_context()
            t = threading.Thread(
                target=ctx.run, args=(self._worker, work),
                name=f"tx-ingest-{i}", daemon=True,
            )
            self._threads.append(t)
            t.start()
        pending: list[tuple[tuple[int, int], PipelineChunk]] = []
        done_shards: dict[int, int] = {}
        cursor = [0, 0]  # next (shard, chunk) due in source order

        def _ready():
            """Pop every heap chunk that is next in source order,
            advancing the cursor past shards whose chunk count is
            known-complete (including zero-chunk shards)."""
            while True:
                while (cursor[0] in done_shards
                       and cursor[1] >= done_shards[cursor[0]]):
                    cursor[0] += 1
                    cursor[1] = 0
                if pending and pending[0][0] == (cursor[0], cursor[1]):
                    _, nxt = heapq.heappop(pending)
                    cursor[1] += 1
                    yield nxt
                    continue
                return

        try:
            while True:
                while True:
                    try:
                        kind, item = self._queue.get(timeout=POLL_S)
                        break
                    except queue.Empty:
                        # an empty buffer is ACCOUNTED consumer stall
                        self.stats.record_stall("consumer", POLL_S)
                        self._m_cons_stall.inc(POLL_S * 1e3)
                if kind == "error":
                    raise item
                if kind == "done":
                    break
                if kind == "shard_done":
                    sid, n_chunks = item
                    done_shards[sid] = n_chunks
                    if self.ordered:
                        yield from _ready()
                    continue
                self.stats._add(chunks=1, rows=item.n_rows)
                self._m_chunks.inc()
                if not self.ordered:
                    yield item
                    continue
                heapq.heappush(pending, (item.order_key, item))
                yield from _ready()
            # drain any ordered tail (shard_done for earlier shards may
            # arrive after later shards' chunks)
            while pending:
                _, nxt = heapq.heappop(pending)
                yield nxt
        finally:
            self._stop.set()
            # drain-join-drain: a producer whose bounded put was already
            # in flight when stop was raised may still land one item, so
            # keep draining until every worker has exited (join bounded
            # by _JOIN_S total - the pipeline can never wedge teardown)
            deadline = time.perf_counter() + _JOIN_S
            while True:
                while True:
                    try:
                        self._queue.get(timeout=0.0)
                    except queue.Empty:
                        break
                alive = [t for t in self._threads if t.is_alive()]
                if not alive or time.perf_counter() > deadline:
                    break
                alive[0].join(timeout=POLL_S)
            if owns_cap:
                _release_thread_cap()
            self.stats._add(wall_s=time.perf_counter() - t_start)

    # -- quarantine merge ----------------------------------------------------
    def merged_quarantine(self) -> QuarantineBuffer:
        """One buffer with EXACT global counts and stable global row
        indices (shard-concatenation order), independent of shard
        completion order: shards merge sorted by shard_id, local row
        indices offset by the preceding shards' seen-row counts."""
        merged = QuarantineBuffer(
            max_rows=max((b.max_rows for b in
                          self.shard_quarantines.values()),
                         default=1024),
            source="+".join(s.path for s in self.shards),
        )
        offset = 0
        for spec in self.shards:
            buf = self.shard_quarantines.get(spec.shard_id)
            if buf is None:
                continue
            snap = buf.snapshot()
            for row in snap["rows"]:
                merged.add(offset + row["row_index"], row["reason"],
                           row["column"], row["excerpt"])
            # counts past the per-shard detail cap stay EXACT: roll the
            # undetailed remainder straight into total/by_reason
            extra = snap["total"] - len(snap["rows"])
            if extra:
                detailed: dict[str, int] = {}
                for row in snap["rows"]:
                    detailed[row["reason"]] = (
                        detailed.get(row["reason"], 0) + 1)
                with merged._lock:
                    merged.total += extra
                    for reason, cnt in snap["by_reason"].items():
                        undetailed = cnt - detailed.get(reason, 0)
                        if undetailed:
                            merged.by_reason[reason] = (
                                merged.by_reason.get(reason, 0)
                                + undetailed)
            offset += self._shard_rows_seen.get(
                spec.shard_id,
                snap["total"] + self.stats.shards.get(
                    spec.shard_id, {}).get("rows_kept", 0),
            )
        return merged


# ---------------------------------------------------------------------------
# consumers
# ---------------------------------------------------------------------------
def pipelined_columns(pipeline: InputPipeline) -> dict:
    """Drain a pipeline into Dataset columns with DETERMINISTIC row
    order (shard-concatenation order) without serializing the
    interleave: chunks are consumed as they land, buffered by their
    (shard_id, chunk_id) key, and concatenated in sorted order — the
    single final concat is the only ordered step."""
    parts: list[tuple[tuple[int, int], CsvChunk]] = []
    for pc in pipeline.chunks():
        parts.append((pc.order_key, pc.payload))
    parts.sort(key=lambda kv: kv[0])
    return assemble_columns(
        pipeline.wanted, pipeline.schema, (c for _, c in parts),
    )


def stack_chunk_columns(chunk: CsvChunk,
                        columns: Sequence[str]) -> np.ndarray:
    """[k, n] float64 matrix from a chunk's numeric columns: one
    contiguous copy per column (each column is already a contiguous
    slice of the scan buffer), NO [n, k] strided transpose fill — the
    cheap map-stage feed for streamed sufficient-statistics consumers
    (Gram/moment accumulators).  Masked slots hold 0 and literal-NaN
    cells are zeroed, the design-matrix missing-value contract."""
    A = np.vstack([chunk.numeric[c][0] for c in columns])
    if np.isnan(A).any():
        np.nan_to_num(A, copy=False)
    return A


def pipelined_design_matrix(
    pipeline: InputPipeline,
    columns: Sequence[str],
    on_block: Optional[Callable[[np.ndarray, np.ndarray], None]] = None,
) -> tuple[np.ndarray, np.ndarray, int]:
    """Drain a pipeline into ([n, d] float32, [n, d] bool present-mask,
    rows) in deterministic shard order.  The CsvChunk → block decode
    runs in the WORKERS when the pipeline was built with
    ``transform=chunk_to_block``-style hooks; otherwise it runs here.
    ``on_block`` observes each block as it lands (streamed consumers:
    CV fold construction, moment accumulators) before assembly."""
    blocks: list[tuple[tuple[int, int], np.ndarray, np.ndarray]] = []
    for pc in pipeline.chunks():
        payload = pc.payload
        if isinstance(payload, CsvChunk):
            block, mask = chunk_to_block(payload, columns)
        else:
            block, mask = payload
        if on_block is not None:
            on_block(block, mask)
        blocks.append((pc.order_key, block, mask))
    blocks.sort(key=lambda kv: kv[0])
    n = sum(b.shape[0] for _, b, _ in blocks)
    d = len(columns)
    X = np.empty((n, d), np.float32)
    M = np.empty((n, d), bool)
    at = 0
    for _, b, m in blocks:
        X[at:at + b.shape[0]] = b
        M[at:at + m.shape[0]] = m
        at += b.shape[0]
    return X, M, n


class PipelinedCSVReader:
    """Reader-protocol adapter over the sharded pipeline: drop-in where
    a CSVReader goes (``OpWorkflow.set_reader``), parsing all shards in
    parallel while the dataset materializes (reference: DataReader.
    generateDataFrame's partitioned read, rebuilt as thread interleave).

    Feature types are restricted to numeric/text like the native fast
    path.  Row order of the produced Dataset is the deterministic
    shard-concatenation order, identical to reading the shards
    sequentially — pinned by the serial-vs-pipelined parity tests.

    ``stream_dataset`` is the workflow streaming-ingest seam: yields
    (PipelineChunk, chunk Dataset) pairs as they land, so train() can
    overlap vectorizer stat accumulation with parsing.
    """

    def __init__(
        self,
        paths: Sequence[str],
        workers: int = DEFAULT_WORKERS,
        buffer_chunks: int = DEFAULT_BUFFER_CHUNKS,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        chunk_rows: int = DEFAULT_CHUNK_ROWS,
        errors: str = "coerce",
        telemetry=None,
        use_native: bool = True,
        fmt: Optional[str] = None,
    ) -> None:
        self.paths = list(paths)
        self.workers = workers
        self.buffer_chunks = buffer_chunks
        self.chunk_bytes = chunk_bytes
        self.chunk_rows = chunk_rows
        self.errors = check_errors_mode(errors)
        self.telemetry = telemetry
        self.use_native = use_native
        self.fmt = fmt
        self.last_pipeline: Optional[InputPipeline] = None

    def _pipeline(self, raw_features) -> InputPipeline:
        schema = {}
        for f in raw_features:
            if f.ftype.kind not in ("numeric", "text"):
                raise TypeError(
                    "PipelinedCSVReader supports numeric/text features; "
                    f"{f.name} is {f.ftype.__name__}"
                )
            schema[f.name] = f.ftype
        pipe = InputPipeline(
            shard(self.paths, fmt=self.fmt), schema,
            workers=self.workers, buffer_chunks=self.buffer_chunks,
            chunk_bytes=self.chunk_bytes, chunk_rows=self.chunk_rows,
            errors=self.errors, telemetry=self.telemetry,
            use_native=self.use_native,
        )
        self.last_pipeline = pipe
        return pipe

    def generate_dataset(self, raw_features, params=None):
        from ..types.dataset import Dataset

        with _obs_trace.span(
            "ingest.read", source=f"{len(self.paths)} shards",
            format="csv_pipeline", errors=self.errors,
        ) as sp:
            cols = pipelined_columns(self._pipeline(raw_features))
            ds = Dataset(cols)
            sp.set_attr("rows", len(ds))
            return ds

    def stream_dataset(self, raw_features, params=None):
        """Yield (PipelineChunk, Dataset-of-that-chunk) as chunks land
        (arrival order, NOT source order — the consumer reorders by
        ``chunk.order_key`` where determinism matters)."""
        from ..types.dataset import Dataset

        pipe = self._pipeline(raw_features)
        names = pipe.wanted
        schema = pipe.schema
        for pc in pipe.chunks():
            cols = assemble_columns(names, schema, [pc.payload])
            yield pc, Dataset(cols)

    def merged_quarantine(self) -> Optional[QuarantineBuffer]:
        if self.last_pipeline is None:
            return None
        return self.last_pipeline.merged_quarantine()
