"""Raw-feature origin stage.

Counterpart of the reference FeatureGeneratorStage (reference: features/.../
stages/FeatureGeneratorStage.scala:60-109): the DAG origin node holding the
extraction function from a raw record plus an optional event aggregator and
time window.  In the TPU rebuild extraction is columnar: ``extract_col``
receives the raw record *table* (Dataset or mapping of python lists) and
returns the feature's Column.  Generators run at ingest (reader) time, never
inside fit layers.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Type

from ..features.feature import Feature
from ..types.columns import Column, column_from_list
from ..types.feature_types import FeatureType
from .base import PipelineStage


class FeatureGeneratorStage(PipelineStage):
    def __init__(
        self,
        feature_name: str,
        output_type: Type[FeatureType],
        extract_fn: Optional[Callable[[Any], Any]] = None,
        is_response: bool = False,
        aggregator: Optional[Any] = None,
        aggregate_window: Optional[float] = None,
        uid: Optional[str] = None,
    ) -> None:
        super().__init__(operation_name="FeatureGenerator", uid=uid)
        self.feature_name = feature_name
        self.output_type = output_type
        self.extract_fn = extract_fn
        self.is_response = is_response
        self.aggregator = aggregator
        self.aggregate_window = aggregate_window

    def get_output(self) -> Feature:
        if self._output is None:
            self._output = Feature(
                name=self.feature_name,
                ftype=self.output_type,
                is_response=self.is_response,
                origin_stage=self,
                parents=(),
            )
        return self._output

    def extract_column(self, records: Sequence[Any]) -> Column:
        """Row-wise extraction from raw records (reader path for custom
        extract functions; columnar readers bypass this)."""
        fn = self.extract_fn or (lambda rec: rec.get(self.feature_name))
        return column_from_list([fn(r) for r in records], self.output_type)
