"""Stage abstractions: Transformer / Estimator with typed feature IO.

TPU-native counterpart of OpPipelineStage{1..N} and the base stage classes
(reference: features/.../stages/OpPipelineStages.scala:176-616 and
features/.../stages/base/*).  Differences by design:

* ``transform`` is *columnar*: it receives the whole Dataset and returns one
  Column - the analog of the reference's row-level ``OpTransformer.
  transformRow`` (OpPipelineStages.scala:592-616) but vectorized, so a DAG
  layer executes as a handful of array ops instead of a fused per-row
  closure (FitStagesUtil.scala:96-119).
* Estimators fit on columnar data (optionally on device via JAX) and return a
  fitted Transformer (the "Model"), carrying summary metadata.
* Every stage owns a ``params`` dict (reference Spark ``Param``s) and a
  ``metadata`` dict - the summary-metadata channel consumed by
  ModelInsights (reference: SanityChecker.scala:677, ModelSelector.scala:189).
"""
from __future__ import annotations

import copy as _copy
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence, Type

from ..features.feature import Feature
from ..types.columns import Column
from ..types.dataset import Dataset
from ..types.feature_types import FeatureType
from ..utils.uid import make_uid

#: env-key suffix for a numeric feature's validity mask in the lowered
#: (fused) array representation - see :class:`Lowering`
MASK_SUFFIX = "@mask"
#: env-key suffixes for a Prediction output's auxiliary arrays
RAW_SUFFIX = "@raw"
PROB_SUFFIX = "@prob"


@dataclass(frozen=True)
class Lowering:
    """A fitted stage compiled down to a pure array function.

    The compile-to-kernel seam (ROADMAP item 1, the Flare-style
    whole-pipeline fusion of arXiv 1703.08219): a fitted Transformer
    that can express its transform as a closed-over function over
    named numpy arrays returns one of these from :meth:`Transformer.
    lower`, and the PipelineCompiler (local/fused.py) fuses every
    lowered stage of a fitted plan into ONE program per shape bucket -
    no Column/Dataset boxing between stages.

    The environment is a flat ``dict[str, np.ndarray]`` keyed by
    feature name, with auxiliary arrays under suffixed keys:

    * numeric feature ``f``  -> ``f``: float64 [n] (masked slots hold
      0.0, matching NumericColumn's canonical form), ``f@mask``:
      bool [n]
    * text feature ``f``     -> ``f``: object [n] host list or array
      (None = missing; consumers iterate element-wise either way)
    * list-ish feature ``f`` -> ``f``: object [n] of tuples/frozensets
    * vector feature ``f``   -> ``f``: float32 [n, d]
    * prediction output ``f``-> ``f``: float64 [n], plus optional
      ``f@raw`` / ``f@prob``: float64 [n, k]

    ``fn`` receives the env and returns the new entries to merge into
    it; it must be pure (no mutation of its inputs) so a fused program
    can be replayed per shape bucket and cached.  ``signature``
    documents the dtype/shape contract per produced key for telemetry
    and debugging.
    """

    fn: Callable[[dict], dict]
    inputs: tuple  # env keys read
    outputs: tuple  # env keys written
    signature: dict = field(default_factory=dict)  # key -> "dtype[shape]"


@dataclass(frozen=True)
class XlaLowering:
    """A fitted stage compiled to a jax-traceable array function.

    The accelerator half of the compile-to-kernel seam (ROADMAP item 3,
    the arXiv 1810.09868 whole-program-to-XLA move): where
    :class:`Lowering` closes over numpy, an ``XlaLowering.fn`` must be
    traceable by ``jax.jit`` - pure jnp ops over a flat dict of numeric
    arrays, no host python on any value.  The XLA pipeline compiler
    (local/fused_xla.py) chains every device-lowered stage into ONE
    jitted program per shape bucket, AOT-compiles it, and serializes
    the executable into the model artifact.

    The env contract narrows to what can cross the XLA boundary:
    float64 [n] values + bool [n] ``@mask`` companions for numerics,
    float32 [n, d] vectors, float64 [n(, k)] prediction arrays.  Text
    and list features never enter the device program: stages consuming
    them (one-hot pivots, string indexer) keep their numpy
    :class:`Lowering` and run as HOST PRE-STEPS whose numeric outputs
    feed the jitted program as inputs - the compiler rejects (with
    FusionError -> numpy-fused fallback) any host stage that would
    need a device-produced key.

    ``fn`` runs under x64 (float64 end to end); it must mirror the
    numpy lowering's arithmetic closely enough that parity stays
    within the pinned ULP budgets of tests/test_fused_xla.py.
    """

    fn: Callable[[dict], dict]
    inputs: tuple  # env keys read
    outputs: tuple  # env keys written
    signature: dict = field(default_factory=dict)  # key -> "dtype[shape]"


class PipelineStage:
    """Base of all stages: uid, typed inputs, single typed output feature."""

    # subclasses declare expected input types; None disables checking
    input_types: Optional[Sequence[Type[FeatureType]]] = None
    output_type: Type[FeatureType] = FeatureType

    def __init__(
        self,
        operation_name: Optional[str] = None,
        uid: Optional[str] = None,
        **params: Any,
    ) -> None:
        cls = type(self).__name__
        self.operation_name = operation_name or cls
        self.uid = uid or make_uid(cls)
        self.params: dict[str, Any] = dict(params)
        self.metadata: dict[str, Any] = {}
        self.input_features: tuple[Feature, ...] = ()
        self._output: Optional[Feature] = None

    # -- params -------------------------------------------------------------
    def set(self, **params: Any) -> "PipelineStage":
        self.params.update(params)
        return self

    def get(self, name: str, default: Any = None) -> Any:
        return self.params.get(name, default)

    # -- wiring -------------------------------------------------------------
    def check_input_types(self, features: Sequence[Feature]) -> None:
        if self.input_types is None:
            return
        expected = list(self.input_types)
        if len(expected) and expected[-1] is Ellipsis:  # variadic tail
            tail_t = expected[-2]
            expected = expected[:-2] + [tail_t] * max(
                0, len(features) - len(expected) + 2
            )
        if len(expected) != len(features):
            raise TypeError(
                f"{self.operation_name} expects {len(expected)} inputs, "
                f"got {len(features)}"
            )
        for f, t in zip(features, expected):
            if not issubclass(f.ftype, t):
                raise TypeError(
                    f"{self.operation_name} input {f.name!r} has type "
                    f"{f.ftype.__name__}, expected {t.__name__}"
                )

    def set_input(self, *features: Feature) -> "PipelineStage":
        self.check_input_types(features)
        self.input_features = tuple(features)
        self._output = None
        return self

    def make_output_name(self) -> str:
        ins = "-".join(f.name for f in self.input_features)[:80]
        return f"{ins}_{self.operation_name}_{self.uid}"

    def get_output(self) -> Feature:
        if self._output is None:
            if not self.input_features:
                raise ValueError(f"stage {self.uid} has no inputs set")
            self._output = Feature(
                name=self.make_output_name(),
                ftype=self.output_type,
                is_response=any(f.is_response for f in self.input_features),
                origin_stage=self,
                parents=self.input_features,
            )
        return self._output

    @property
    def output_name(self) -> str:
        return self.get_output().name

    def input_columns(self, ds: Dataset) -> list[Column]:
        return [ds[f.name] for f in self.input_features]

    def copy(self) -> "PipelineStage":
        # Spark's defaultCopy copies the param map: mutating a copy's params
        # or metadata must never leak into the original stage
        new = _copy.copy(self)
        new.params = _copy.deepcopy(self.params)
        new.metadata = _copy.deepcopy(self.metadata)
        return new

    def __repr__(self) -> str:
        ins = ", ".join(f.name for f in self.input_features)
        return f"{type(self).__name__}(uid={self.uid}, in=[{ins}])"


class Transformer(PipelineStage):
    """A stage with a pure columnar transform."""

    def transform_columns(self, cols: Sequence[Column], ds: Dataset) -> Column:
        raise NotImplementedError

    def transform(self, ds: Dataset) -> Dataset:
        col = self.transform_columns(self.input_columns(ds), ds)
        return ds.with_column(self.output_name, col)

    def lower(self) -> Optional[Lowering]:
        """Compile this FITTED stage to a pure array function, or None
        when it cannot be lowered (the pipeline then serves through the
        interpreted stage-by-stage path).  Implementations must produce
        bit-identical arrays to ``transform_columns`` - parity is pinned
        by tests/test_fused_pipeline.py."""
        return None

    def lower_xla(self) -> Optional[XlaLowering]:
        """Compile this FITTED stage to a jax-traceable array function,
        or None when it has no device lowering.  A None is NOT a
        pipeline-wide failure: when the stage's numpy :meth:`lower`
        consumes only host-available keys (raw decodes or other host
        outputs), the XLA compiler runs it as a host pre-step feeding
        the jitted program - the route every text/one-hot stage takes."""
        return None


class Estimator(PipelineStage):
    """A stage that must observe data to produce a fitted Transformer."""

    #: estimators whose fit-time statistics are mergeable across row
    #: chunks flip this True and implement :meth:`partial_fit_chunk`:
    #: the workflow's streaming ingest mode (readers/pipeline.py) then
    #: accumulates their statistics WHILE shards parse, and the
    #: subsequent fit() consumes the merged stats instead of re-scanning
    #: the materialized columns — the ingest/fit overlap seam.
    streaming_fittable = False

    #: merged streaming statistics, set by :meth:`accept_partial_fits`
    #: and consumed EXACTLY ONCE by the next fit (one-shot so a later
    #: refit — e.g. a CV fold refit on a subset — can never silently
    #: reuse full-data statistics)
    _streamed_stats = None

    def partial_fit_chunk(self, cols: Sequence[Column], ds: Dataset):
        """Pure per-chunk fit statistics (no state mutation): whatever
        :meth:`accept_partial_fits` can merge deterministically."""
        raise NotImplementedError(
            f"{type(self).__name__} is not streaming-fittable"
        )

    def accept_partial_fits(self, stats: Sequence) -> None:
        """Install chunk statistics (in deterministic source order) for
        the next fit.  Default merge: hand the ordered list to
        fit_model via ``_streamed_stats``; stages override
        ``_merge_partial_fits`` for their stat shape."""
        self._streamed_stats = self._merge_partial_fits(list(stats))

    def _merge_partial_fits(self, stats: list):
        return stats

    def _take_streamed(self):
        """Pop the installed streaming statistics (None when absent)."""
        s = self._streamed_stats
        self._streamed_stats = None
        return s

    def fit_model(self, cols: Sequence[Column], ds: Dataset) -> "Transformer":
        raise NotImplementedError

    def fit(self, ds: Dataset) -> "Transformer":
        model = self.fit_model(self.input_columns(ds), ds)
        # fitted model takes over the estimator's place in the DAG: same
        # output feature + uid mapping (reference: fitted stages replace
        # estimators in OpWorkflowModel.setStages)
        model.input_features = self.input_features
        model._output = self._output
        model.uid = self.uid  # fitted model keeps the stage's uid in the DAG
        model.operation_name = self.operation_name
        if not model.metadata:
            model.metadata = dict(self.metadata)
        return model

    # Some estimators want holdout evaluation after fit (reference
    # HasTestEval, FitStagesUtil.scala:266-268)
    has_test_eval = False


class LambdaTransformer(Transformer):
    """Arity-agnostic transformer from a columnar function.  The function
    receives the input Columns and must return a Column.  Used by the DSL's
    feature math; ``operation_name`` doubles as the serialization key."""

    def __init__(
        self,
        fn,
        output_type: Type[FeatureType],
        operation_name: str = "lambda",
        input_types: Optional[Sequence[Type[FeatureType]]] = None,
        uid: Optional[str] = None,
        **params: Any,
    ) -> None:
        super().__init__(operation_name=operation_name, uid=uid, **params)
        self.fn = fn
        self.output_type = output_type
        if input_types is not None:
            self.input_types = input_types

    def transform_columns(self, cols: Sequence[Column], ds: Dataset) -> Column:
        return self.fn(*cols)
