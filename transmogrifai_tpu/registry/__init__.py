"""Versioned model registry: publish → canary → stable → rollback.

The lifecycle layer that composes the robustness machinery of the last
four rounds into a closed control loop (reference frame: TF-Serving's
versioned servable store + health-gated version advance, PAPERS.md —
the reference itself treats a fitted model as a terminal artifact):

* :class:`ModelRegistry` — a versioned, content-addressed store layered
  on the crash-consistent ``serialization/model_io.py`` artifacts; each
  version records its manifest SHA-256, schema-contract hash, eval
  metrics, parent version, and stage lineage in a checksummed
  ``registry.json`` updated by atomic rename (``.last-good`` recovery,
  drilled by the ``registry.publish_crash`` fault point).
* :class:`DeploymentController` — zero-downtime hot-swap of the live
  compiled endpoint generation (in-flight batches finish on the old
  generation; the swap never drops or double-scores a request),
  deterministic hash-based canary traffic splits, and optional shadow
  scoring that records candidate-vs-stable output deltas without
  touching responses.
* :class:`RollbackPolicy` — automatic canary demotion when live signals
  (breaker trips, NaN-guard hits, JS drift, p99 latency ratio) breach
  SLO relative to stable, with the decision + evidence recorded in
  telemetry, ``summary_json()``, and the registry lineage.

CLI: ``python -m transmogrifai_tpu.cli registry list|verify|promote|
rollback``; runner: the ``deploy`` run type; evidence: ``python
bench.py --registry`` → ``REGISTRY_BENCH.json``.
"""
from .deployment import DeploymentController, Generation, route_key
from .rollback import RollbackDecision, RollbackPolicy
from .store import (
    ModelRegistry,
    RegistryError,
    RegistryIntegrityError,
    RegistryVersion,
)

__all__ = [
    "DeploymentController",
    "Generation",
    "ModelRegistry",
    "RegistryError",
    "RegistryIntegrityError",
    "RegistryVersion",
    "RollbackDecision",
    "RollbackPolicy",
    "route_key",
]
