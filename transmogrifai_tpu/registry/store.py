"""Versioned, content-addressed model registry over crash-consistent artifacts.

The model-lifecycle layer TensorFlow's production story hinges on
(reference frame: TF-Serving's versioned servable store + TensorFlow
§4.2 user-level checkpointing, PAPERS.md; the reference's own
OpWorkflowModelWriter persists one terminal artifact and stops there):
a fitted model is not a terminal artifact but ONE VERSION in a lineage
that advances (publish → canary → stable) and reverts (rollback)
while serving.

Layout under ``root``::

    registry.json             # the version index (checksummed, see below)
    registry.json.last-good   # previous index (crash recovery)
    versions/v<N>/            # one crash-consistent model_io artifact each

Every version entry records the artifact's ``manifest.json`` SHA-256
(content address: the manifest already checksums every payload file, so
hashing it pins the whole artifact), the schema-contract hash, eval
metrics, the parent version it was derived from, and its stage lineage.
``registry.json`` itself follows the same crash-consistency discipline
as ``serialization/model_io.py``: a self-checksum over the canonical
payload, tempfile write + fsync + atomic rename, with the previous
index surviving as ``registry.json.last-good`` — a crash at ANY instant
(drilled via the ``registry.publish_crash`` fault point, which kills
between the artifact publish and the index commit) leaves the registry
loadable at the prior version, with the orphaned artifact directory
reported by :meth:`ModelRegistry.verify` rather than trusted.

Stage machine (see docs/registry.md)::

    publish → candidate ─ promote(to="canary") → canary ─ promote → stable
                   └─────────── promote(to="stable") ──────────────┘
    canary ─ rollback → rolled_back        stable ─ rollback → rolled_back
                                           (stable pointer reverts to parent)

Writers serialize at two levels: an in-process RLock, plus an exclusive
``flock(2)`` on ``registry.lock`` held across every read-modify-write —
the CLI (``tx registry promote/rollback``) is a second PROCESS mutating
the same index, and without the file lock its stale read-modify-write
could silently drop a concurrently published version.  The atomic-
rename commit keeps concurrent READERS consistent without any lock.
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ..faults import injection as _faults
from ..obs import trace as _obs_trace
from ..serialization.model_io import (
    MANIFEST_JSON,
    SCHEMA_JSON,
    XLA_CACHE_JSON,
    _fsync_dir,
    _sha256,
    _sha256_file,
    _write_fsync,
    load_model,
    save_model,
    verify_artifact,
)

log = logging.getLogger("transmogrifai_tpu.registry")

REGISTRY_JSON = "registry.json"
REGISTRY_LOCK = "registry.lock"
LAST_GOOD_SUFFIX = ".last-good"
VERSIONS_DIR = "versions"

REGISTRY_FORMAT_VERSION = 1

#: lineage events kept in registry.json (bounded: the registry index
#: must stay small enough to read on every serve-plane decision)
MAX_LINEAGE_EVENTS = 512

STAGES = ("candidate", "canary", "stable", "retired", "rolled_back")


class RegistryError(RuntimeError):
    """A registry operation failed; the message names version + reason."""


class RegistryIntegrityError(RegistryError):
    """registry.json failed its checksum and no last-good copy could
    recover it (truncation, bit-flips, partial overwrite)."""


@dataclass
class RegistryVersion:
    """One published model version's index entry."""

    version: str
    path: str  # relative to the registry root
    created_at: float
    manifest_sha256: str
    schema_sha256: Optional[str] = None
    metrics: dict = field(default_factory=dict)
    parent: Optional[str] = None
    stage: str = "candidate"

    def to_json(self) -> dict:
        return {
            "version": self.version,
            "path": self.path,
            "created_at": self.created_at,
            "manifest_sha256": self.manifest_sha256,
            "schema_sha256": self.schema_sha256,
            "metrics": dict(self.metrics),
            "parent": self.parent,
            "stage": self.stage,
        }

    @staticmethod
    def from_json(doc: dict) -> "RegistryVersion":
        return RegistryVersion(
            version=doc["version"],
            path=doc["path"],
            created_at=float(doc.get("created_at", 0.0)),
            manifest_sha256=doc["manifest_sha256"],
            schema_sha256=doc.get("schema_sha256"),
            metrics=dict(doc.get("metrics", {})),
            parent=doc.get("parent"),
            stage=doc.get("stage", "candidate"),
        )


def _doc_checksum(doc: dict) -> str:
    """Self-checksum over the canonical payload (everything except the
    checksum field itself)."""
    payload = {k: v for k, v in doc.items() if k != "checksum"}
    return _sha256(
        json.dumps(payload, sort_keys=True, default=str).encode("utf-8")
    )


class ModelRegistry:
    """Versioned model store + stage lineage over one root directory."""

    def __init__(self, root: str, create: bool = True) -> None:
        self.root = os.path.abspath(root)
        self._lock = threading.RLock()
        self._flock_warned = False
        index = os.path.join(self.root, REGISTRY_JSON)
        if not os.path.exists(index):
            if not create:
                raise RegistryError(f"no registry at {self.root}")
            os.makedirs(os.path.join(self.root, VERSIONS_DIR), exist_ok=True)
            with self._exclusive():
                if not os.path.exists(index):  # raced creator won
                    self._commit(self._empty_doc())

    # -- locking ------------------------------------------------------------
    @contextlib.contextmanager
    def _exclusive(self):
        """In-process RLock + exclusive flock on ``registry.lock``: every
        read-modify-write (publish/promote/rollback) holds both, so a
        concurrent mutation from ANOTHER process (the operator CLI) can
        never interleave its stale read with our commit and drop an
        entry.  On filesystems without flock support the file lock
        degrades to in-process-only with a one-time warning."""
        with self._lock:
            lock_fd = None
            try:
                try:
                    import fcntl

                    lock_fd = os.open(
                        os.path.join(self.root, REGISTRY_LOCK),
                        os.O_RDWR | os.O_CREAT, 0o644,
                    )
                    fcntl.flock(lock_fd, fcntl.LOCK_EX)
                except (ImportError, OSError) as e:
                    if not self._flock_warned:
                        self._flock_warned = True
                        log.warning(
                            "registry %s: no cross-process file lock "
                            "(%s); concurrent writers from other "
                            "processes are unsafe", self.root, e,
                        )
                    if lock_fd is not None:
                        os.close(lock_fd)
                        lock_fd = None
                yield
            finally:
                if lock_fd is not None:
                    os.close(lock_fd)  # releases the flock

    # -- index IO -----------------------------------------------------------
    @staticmethod
    def _empty_doc() -> dict:
        return {
            "format_version": REGISTRY_FORMAT_VERSION,
            "versions": {},
            "stable": None,
            "canary": None,
            "lineage": [],
        }

    def _index_path(self) -> str:
        return os.path.join(self.root, REGISTRY_JSON)

    @staticmethod
    def _verify_bytes(data: bytes) -> Optional[dict]:
        """Parse + checksum-verify index bytes; None when damaged."""
        try:
            doc = json.loads(data.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return None
        if not isinstance(doc, dict) or "versions" not in doc:
            return None
        if doc.get("checksum") != _doc_checksum(doc):
            return None
        return doc

    @classmethod
    def _verify_doc(cls, path: str) -> Optional[dict]:
        """Parse + checksum-verify one index file; None when damaged."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return None
        return cls._verify_bytes(data)

    def _read(self) -> dict:
        """The verified index, recovering from ``.last-good`` when the
        primary is damaged (the model_io resolve_artifact discipline)."""
        path = self._index_path()
        doc = self._verify_doc(path)
        if doc is not None:
            return doc
        last_good = path + LAST_GOOD_SUFFIX
        doc = self._verify_doc(last_good)
        if doc is not None:
            log.warning(
                "registry index %s failed verification; recovered from "
                "last-good copy %s", path, last_good,
            )
            return doc
        raise RegistryIntegrityError(
            f"registry index {path} failed its checksum and no last-good "
            "copy could recover it"
        )

    def _commit(self, doc: dict) -> None:
        """Atomic index update: last-good snapshot of the current index,
        then tempfile + fsync + rename.  A crash at any instant leaves a
        verifiable index (old or new)."""
        doc["format_version"] = REGISTRY_FORMAT_VERSION
        doc["updated_at"] = time.time()
        doc["checksum"] = _doc_checksum(doc)
        path = self._index_path()
        data = json.dumps(doc, indent=1, sort_keys=True,
                          default=str).encode("utf-8")
        if os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    prev = f.read()
                # snapshot ONLY a verified primary: a corrupt primary
                # copied over the last-good would destroy the one copy
                # _read() can still recover from, and a crash in this
                # commit window would then brick the registry
                if self._verify_bytes(prev) is not None:
                    _write_fsync(path + LAST_GOOD_SUFFIX + ".tmp", prev)
                    os.replace(path + LAST_GOOD_SUFFIX + ".tmp",
                               path + LAST_GOOD_SUFFIX)
                else:
                    log.warning(
                        "registry index %s fails verification; keeping "
                        "the existing last-good snapshot", path,
                    )
            except OSError as e:
                log.warning("could not snapshot %s to last-good: %s",
                            path, e)
        tmp = f"{path}.tmp-{os.getpid()}"
        _write_fsync(tmp, data)
        os.replace(tmp, path)
        _fsync_dir(self.root)

    def _append_lineage(self, doc: dict, **event: Any) -> None:
        event["t"] = time.time()
        doc.setdefault("lineage", []).append(event)
        if len(doc["lineage"]) > MAX_LINEAGE_EVENTS:
            del doc["lineage"][0]

    # -- queries ------------------------------------------------------------
    @staticmethod
    def _version_sort_key(vid: str) -> tuple:
        """Canonical ``v<N>`` ids sort numerically; anything else (a
        hand-migrated or future-format id _next_version already warns
        about) sorts after them lexically instead of crashing the
        listing."""
        try:
            return (0, int(vid[1:]), vid)
        except (ValueError, IndexError):
            return (1, 0, vid)

    def versions(self) -> list[RegistryVersion]:
        doc = self._read()
        out = [RegistryVersion.from_json(v) for v in doc["versions"].values()]
        out.sort(key=lambda v: self._version_sort_key(v.version))
        return out

    def get(self, version: str) -> RegistryVersion:
        doc = self._read()
        entry = doc["versions"].get(version)
        if entry is None:
            raise RegistryError(
                f"no version {version!r} in registry {self.root} "
                f"(have: {sorted(doc['versions'])})"
            )
        return RegistryVersion.from_json(entry)

    @property
    def stable(self) -> Optional[str]:
        return self._read().get("stable")

    @property
    def canary(self) -> Optional[str]:
        return self._read().get("canary")

    def lineage(self) -> list[dict]:
        return [dict(e) for e in self._read().get("lineage", [])]

    def artifact_path(self, version: str) -> str:
        return os.path.join(self.root, self.get(version).path)

    # -- publish ------------------------------------------------------------
    def _next_version(self, doc: dict) -> str:
        """Smallest unused ``v<N>``, counting BOTH index entries and
        existing version directories: a reserved-but-uncommitted dir
        (another process mid-publish, or a crash orphan) must never be
        handed out again."""
        names = set(doc["versions"])
        vdir = os.path.join(self.root, VERSIONS_DIR)
        if os.path.isdir(vdir):
            names.update(
                name for name in os.listdir(vdir)
                if not name.endswith(LAST_GOOD_SUFFIX)
            )
        n = 0
        for vid in names:
            try:
                n = max(n, int(vid[1:]))
            except (ValueError, IndexError):
                log.warning("ignoring non-canonical version id %r", vid)
        return f"v{n + 1}"

    def publish(self, model, metrics: Optional[dict] = None,
                parent: Optional[str] = None,
                stage: str = "candidate") -> RegistryVersion:
        """One ``registry.publish`` trace span per publish (obs/): the
        artifact save + index commit ride the ambient run trace, the
        published version tagged on exit."""
        with _obs_trace.span("registry.publish", stage=stage) as sp:
            entry = self._publish(model, metrics=metrics, parent=parent,
                                  stage=stage)
            sp.set_attr("version", entry.version)
            return entry

    def _publish(self, model, metrics: Optional[dict] = None,
                 parent: Optional[str] = None,
                 stage: str = "candidate") -> RegistryVersion:
        """Save ``model`` as the next version and record it in the index.

        The exclusive lock is held only to RESERVE the version id (a
        mkdir marker _next_version respects) and again to commit the
        index entry — never across the artifact write itself, so a
        multi-hundred-MB fsync'd save cannot block an operator's
        concurrent ``tx registry rollback``.  The artifact save is
        crash-consistent on its own (model_io); the index commit is
        atomic on its own; the window BETWEEN them is the publish crash
        window (``registry.publish_crash`` drills it): a crash there
        leaves an orphaned artifact directory the index never
        references — the registry stays at the prior version.
        """
        if stage not in ("candidate", "stable", "canary"):
            raise RegistryError(
                f"cannot publish directly into stage {stage!r}"
            )
        with self._exclusive():
            doc = self._read()
            vid = self._next_version(doc)
            rel = os.path.join(VERSIONS_DIR, vid)
            path = os.path.join(self.root, rel)
            os.makedirs(path)  # the id reservation marker
        save_model(model, path)
        # crash drill: death here (artifact published, index not yet
        # committed) must leave the registry at the prior version
        _faults.inject_kill("registry.publish_crash")
        manifest_sha, _size = _sha256_file(
            os.path.join(path, MANIFEST_JSON)
        )
        schema_path = os.path.join(path, SCHEMA_JSON)
        schema_sha = (
            _sha256_file(schema_path)[0]
            if os.path.exists(schema_path) else None
        )
        with self._exclusive():
            doc = self._read()
            if parent is None:
                parent = doc.get("stable")
            entry = RegistryVersion(
                version=vid,
                path=rel,
                created_at=time.time(),
                manifest_sha256=manifest_sha,
                schema_sha256=schema_sha,
                metrics=dict(metrics or {}),
                parent=parent,
                stage="candidate",
            )
            doc["versions"][vid] = entry.to_json()
            self._append_lineage(doc, event="publish", version=vid,
                                 parent=parent)
            self._commit(doc)
        # outside the lock: promote() takes it again, and a second flock
        # on the same file would deadlock against our own fd
        self._attribute_telemetry(vid)
        if stage != "candidate":
            return self.promote(vid, to=stage)
        return entry

    @staticmethod
    def _attribute_telemetry(version: str) -> None:
        """Stamp the process-wide mesh/data accumulators with the
        version just published: the degraded-training events and ingest
        counts recorded by THIS process produced this version, and
        every later snapshot/export should say so (the ServingTelemetry
        side is stamped per generation by the DeploymentController).
        Best-effort — scoring-only installs may strip the parallel
        tier."""
        try:
            from ..schema.quarantine import data_telemetry

            data_telemetry().set_model_version(version)
        except ImportError:
            log.debug("no data telemetry to attribute %s to", version)
        try:
            from ..parallel.resilience import mesh_telemetry

            mesh_telemetry().set_model_version(version)
        except ImportError:
            log.debug("no mesh telemetry to attribute %s to", version)

    # -- stage transitions --------------------------------------------------
    def promote(self, version: str, to: str = "stable") -> RegistryVersion:
        """candidate → canary, candidate/canary → stable.  Promoting to
        stable retires the previous stable (still loadable — rollback
        may revert to it) and clears the canary pointer when the canary
        itself was promoted."""
        if to not in ("stable", "canary"):
            raise RegistryError(f"cannot promote to stage {to!r}")
        with self._exclusive():
            doc = self._read()
            entry = doc["versions"].get(version)
            if entry is None:
                raise RegistryError(f"no version {version!r} to promote")
            allowed = ("candidate", "canary") if to == "stable" else (
                "candidate",)
            if entry["stage"] not in allowed:
                raise RegistryError(
                    f"cannot promote {version} from stage "
                    f"{entry['stage']!r} to {to!r} (allowed from: "
                    f"{allowed})"
                )
            err = verify_artifact(os.path.join(self.root, entry["path"]))
            if err is not None:
                raise RegistryIntegrityError(
                    f"refusing to promote {version}: {err}"
                )
            from_stage = entry["stage"]
            entry["stage"] = to
            if to == "stable":
                prev = doc.get("stable")
                if prev and prev != version and prev in doc["versions"]:
                    doc["versions"][prev]["stage"] = "retired"
                doc["stable"] = version
                if doc.get("canary") == version:
                    doc["canary"] = None
            else:
                prev_canary = doc.get("canary")
                if prev_canary and prev_canary != version:
                    raise RegistryError(
                        f"canary slot already held by {prev_canary}; "
                        "roll it back or promote it first"
                    )
                doc["canary"] = version
            self._append_lineage(doc, event="promote", version=version,
                                 from_stage=from_stage, to_stage=to)
            self._commit(doc)
            return RegistryVersion.from_json(entry)

    def release_canary(self, reason: str = "") -> Optional[dict]:
        """End a canary observation window UNDECIDED: the version
        returns to ``candidate`` (re-promotable later — unlike a
        rollback, no judgement is recorded against it) and the slot
        frees.  The serve plane calls this when a deploy run ends with
        its canary still live, so a later run's canary never serves
        untracked while the registry still points at the old one."""
        with self._exclusive():
            doc = self._read()
            vid = doc.get("canary")
            if vid is None:
                return None
            doc["canary"] = None
            entry = doc["versions"].get(vid)
            if entry is not None and entry["stage"] == "canary":
                entry["stage"] = "candidate"
            event = {"event": "canary_release", "version": vid,
                     "reason": reason}
            self._append_lineage(doc, **event)
            self._commit(doc)
            log.info("op_registry canary %s released undecided%s", vid,
                     f": {reason}" if reason else "")
            return dict(event)

    def describe(self, lineage: bool = False) -> dict:
        """One consistent read of the whole registry state (stable /
        canary pointers, versions, optionally the lineage) — the CLI's
        ``list`` view.  A single ``_read()`` so the pointers can never
        disagree with the version stages when another process commits
        mid-listing."""
        doc = self._read()
        versions = [RegistryVersion.from_json(v)
                    for v in doc["versions"].values()]
        versions.sort(key=lambda v: self._version_sort_key(v.version))
        out: dict[str, Any] = {
            "root": self.root,
            "stable": doc.get("stable"),
            "canary": doc.get("canary"),
            "versions": [v.to_json() for v in versions],
        }
        if lineage:
            out["lineage"] = [dict(e) for e in doc.get("lineage", [])]
        return out

    def rollback(self, version: Optional[str] = None, reason: str = "",
                 evidence: Optional[dict] = None) -> dict:
        """Demote a version.  Default target: the canary when one is
        live, else the stable.  Rolling back the STABLE reverts the
        stable pointer to the entry's parent (which must verify).  The
        decision + evidence land in the lineage so ``summary_json()``
        consumers can attribute the demotion."""
        with self._exclusive():
            doc = self._read()
            if version is None:
                version = doc.get("canary") or doc.get("stable")
            if version is None:
                raise RegistryError("nothing to roll back: no canary or "
                                    "stable version")
            entry = doc["versions"].get(version)
            if entry is None:
                raise RegistryError(f"no version {version!r} to roll back")
            from_stage = entry["stage"]
            reverted_to = None
            if doc.get("canary") == version:
                doc["canary"] = None
            elif doc.get("stable") == version:
                parent = entry.get("parent")
                if parent is None or parent not in doc["versions"]:
                    raise RegistryError(
                        f"cannot roll back stable {version}: no parent "
                        "version recorded to revert to"
                    )
                parent_stage = doc["versions"][parent]["stage"]
                if parent_stage != "retired":
                    # a parent the operator explicitly demoted
                    # (rolled_back) — or one that never served
                    # (candidate) — must not silently become the
                    # serving stable again
                    raise RegistryError(
                        f"cannot roll back stable {version}: parent "
                        f"{parent} is {parent_stage!r}, not a retired "
                        "ex-stable; promote a known-good version "
                        "explicitly instead"
                    )
                err = verify_artifact(
                    os.path.join(self.root, doc["versions"][parent]["path"])
                )
                if err is not None:
                    raise RegistryIntegrityError(
                        f"cannot roll back to parent {parent}: {err}"
                    )
                doc["versions"][parent]["stage"] = "stable"
                doc["stable"] = parent
                reverted_to = parent
            entry["stage"] = "rolled_back"
            event = {
                "event": "rollback", "version": version,
                "from_stage": from_stage, "reason": reason,
            }
            if reverted_to is not None:
                event["stable_reverted_to"] = reverted_to
            if evidence:
                event["evidence"] = evidence
            self._append_lineage(doc, **event)
            self._commit(doc)
            log.warning(
                "op_registry version %s rolled back from %s%s%s",
                version, from_stage,
                f" (stable reverted to {reverted_to})" if reverted_to
                else "",
                f": {reason}" if reason else "",
            )
            return dict(event)

    # -- verification / loading ---------------------------------------------
    def verify(self, version: Optional[str] = None) -> dict:
        """Checksum-verify the index and version artifacts.

        Returns ``{"index_ok": bool, "versions": {vid: None|error},
        "orphans": [...], "stale_executables": {vid: warning},
        "ok": bool}``.  ``ok`` requires BOTH the primary index and every
        checked version to verify: a registry serving from its
        ``.last-good`` copy is one commit stale (a promote may have
        silently reverted), so it must fail the check loudly even though
        it remains operable.  ``version=None`` checks every recorded
        version; orphaned artifact directories (published but never
        committed — the publish crash window) are reported, never
        trusted.

        ``stale_executables`` names versions whose cached AOT XLA
        executables (``xla_cache.json``, local/fused_xla.py) were built
        by a DIFFERENT jax/jaxlib build or device backend than this
        process runs: loading them will retrace and recache instead of
        warm-starting.  A named WARNING, not damage — the artifact
        itself is intact, so ``ok`` is unaffected."""
        index_ok = self._verify_doc(self._index_path()) is not None
        doc = self._read()
        targets = [version] if version is not None else sorted(
            doc["versions"])
        report: dict[str, Any] = {
            "index_ok": index_ok,
            "recovered_from_last_good": not index_ok,
            "versions": {},
            "orphans": [],
            "stale_executables": {},
        }
        for vid in targets:
            entry = doc["versions"].get(vid)
            if entry is None:
                report["versions"][vid] = "not in the registry index"
                continue
            path = os.path.join(self.root, entry["path"])
            err = verify_artifact(path)
            if err is None:
                sha, _ = _sha256_file(os.path.join(path, MANIFEST_JSON))
                if sha != entry["manifest_sha256"]:
                    err = (
                        f"artifact manifest hash {sha[:12]}… does not "
                        "match the registered version (artifact replaced "
                        "outside the registry)"
                    )
            report["versions"][vid] = err
            if err is None:
                warn = self._stale_executable_warning(path)
                if warn is not None:
                    report["stale_executables"][vid] = warn
                    log.warning("op_registry version %s: %s", vid, warn)
        vdir = os.path.join(self.root, VERSIONS_DIR)
        if version is None and os.path.isdir(vdir):
            known = {e["path"] for e in doc["versions"].values()}
            for name in sorted(os.listdir(vdir)):
                rel = os.path.join(VERSIONS_DIR, name)
                if rel not in known and not name.endswith(
                        LAST_GOOD_SUFFIX) and "tmp" not in name:
                    report["orphans"].append(rel)
        report["ok"] = index_ok and all(
            v is None for v in report["versions"].values())
        return report

    @staticmethod
    def _stale_executable_warning(path: str) -> Optional[str]:
        """Named staleness warning for a version's cached AOT XLA
        executables, or None when absent/current.  Checksum damage is
        the manifest's job (already verified by the caller); this
        compares the cache's recorded jax/jaxlib/backend against the
        running process so the operator learns about a fleet-wide
        retrace BEFORE replicas silently pay it at load."""
        meta_path = os.path.join(path, XLA_CACHE_JSON)
        if not os.path.exists(meta_path):
            return None
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError) as e:
            return f"xla executable cache meta unreadable: {e}"
        cached = meta.get("runtime", {})
        try:
            from ..local.fused_xla import runtime_fingerprint

            current = runtime_fingerprint()
        except Exception as e:  # noqa: BLE001 - verify must not die on jax
            return (f"cannot determine the current runtime to check the "
                    f"xla executable cache against: {e}")
        if cached != current:
            return (
                "stale xla executables: cached for "
                f"jax={cached.get('jax')} jaxlib={cached.get('jaxlib')} "
                f"backend={cached.get('backend')}, this process runs "
                f"jax={current['jax']} jaxlib={current['jaxlib']} "
                f"backend={current['backend']}; loading will retrace "
                "and recache instead of warm-starting"
            )
        return None

    def load(self, version: str, workflow):
        """Restore one version into a code-defined workflow (the
        load_model contract), verifying the registered content address
        first."""
        entry = self.get(version)
        path = os.path.join(self.root, entry.path)
        err = verify_artifact(path)
        if err is not None:
            raise RegistryIntegrityError(
                f"version {version} failed verification: {err}"
            )
        sha, _ = _sha256_file(os.path.join(path, MANIFEST_JSON))
        if sha != entry.manifest_sha256:
            raise RegistryIntegrityError(
                f"version {version} artifact does not match its "
                "registered manifest hash (replaced outside the registry)"
            )
        return load_model(path, workflow)

    def load_stable(self, workflow):
        stable = self.stable
        if stable is None:
            raise RegistryError(f"registry {self.root} has no stable "
                                "version")
        return self.load(stable, workflow)
