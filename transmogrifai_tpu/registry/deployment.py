"""Zero-downtime deployment control over compiled serving endpoints.

The serve-plane half of the registry (reference frame: TF-Serving's
servable manager, which advances versions under live traffic without
dropping requests; the reference's local scoring has no lifecycle at
all): a :class:`DeploymentController` owns the live GENERATIONS — one
stable, optionally one canary — each a fully warmed
:class:`~..serving.endpoint.CompiledEndpoint` with its own
``ServingTelemetry`` and breaker, tagged with the registry version it
serves.

Guarantees:

* **Hot-swap never drops or double-scores.**  Scoring resolves the
  generation pointers ONCE per call under the routing lock and then
  scores on those objects; :meth:`deploy` builds and warms the new
  endpoint entirely OFF-pointer and publishes it with a single pointer
  flip under the same lock.  A batch that resolved the old generation
  finishes on it (the object stays alive as long as any call holds it);
  a batch that resolves after the flip scores on the new one; no batch
  can observe half a swap.  The ``registry.swap_crash`` fault point
  raises inside the swap window to drill that a failed deploy leaves
  the old generation serving untouched.
* **Canary routing is deterministic.**  A record routes to the canary
  iff ``murmur3(canonical-json(record), split_seed) % 10000`` falls
  under ``fraction * 10000`` — the same record always lands on the same
  arm across processes and retries (no flappy per-request coin flips),
  and the split needs no caller-provided request id.
* **Shadow scoring never touches responses.**  With ``shadow=True`` the
  full batch scores on stable (those are the returned results) and the
  candidate scores the same rows on the side; per-row output deltas
  accumulate in :meth:`shadow_stats`.
* **Rollback is automatic and evidenced.**  Every ``check_every_batches``
  scored batches the :class:`~.rollback.RollbackPolicy` compares the
  canary's live telemetry against stable's; a breach demotes the canary
  in one pointer flip, records the decision + evidence in both arms'
  telemetry lifecycle and :meth:`summary_json`, and (when a registry is
  attached) in the registry lineage.  Fault points ``canary.regression``
  (poisons live canary outputs through the same NaN-guard + breaker
  accounting the endpoint applies) and ``canary.latency`` (inflates the
  canary arm's latency inside its timed window) drill the loop end to
  end.
"""
from __future__ import annotations

import json
import logging
import math
import threading
import time
from dataclasses import dataclass
from typing import Any, Mapping, Optional, Sequence

from ..faults import injection as _faults
from ..obs import trace as _obs_trace
from ..serving.endpoint import (
    CompiledEndpoint,
    RowScoringError,
    compile_endpoint,
)
from ..serving.telemetry import ServingTelemetry
from ..utils.hashing import murmur3_32
from .rollback import RollbackDecision, RollbackPolicy
from .store import ModelRegistry, RegistryError

log = logging.getLogger("transmogrifai_tpu.registry")

LOG_PREFIX = "op_registry_metrics"

#: lifecycle events kept on the controller (bounded like telemetry)
_MAX_EVENTS = 256

#: hash-split resolution: fractions quantize to 1/10000 (0.01% traffic)
_SPLIT_BUCKETS = 10000


@dataclass
class Generation:
    """One live deployed model generation."""

    generation: int
    version: str
    endpoint: CompiledEndpoint
    deployed_at: float

    def snapshot(self) -> dict:
        return {
            "generation": self.generation,
            "version": self.version,
            "deployed_at": self.deployed_at,
            "telemetry": self.endpoint.telemetry.snapshot(),
        }


def route_key(record: Mapping[str, Any]) -> str:
    """Canonical routing key for the deterministic canary split (the
    record's sorted-key JSON: stable across dict ordering and
    processes)."""
    return json.dumps(record, sort_keys=True, default=str)


class DeploymentController:
    """Stable/canary generation pointers + deterministic traffic split.

    ``endpoint_kw`` defaults apply to every generation this controller
    compiles (buckets, breaker knobs, drift policy); per-deploy
    overrides ride the ``deploy``/``start_canary`` calls.
    """

    def __init__(
        self,
        registry: Optional[ModelRegistry] = None,
        policy: Optional[RollbackPolicy] = None,
        canary_fraction: float = 0.05,
        shadow: bool = False,
        split_seed: int = 42,
        check_every_batches: int = 8,
        model_id: Optional[str] = None,
        track_registry: bool = True,
        **endpoint_kw: Any,
    ) -> None:
        if not (0.0 <= canary_fraction <= 1.0):
            raise ValueError("canary_fraction must be in [0, 1]")
        self.registry = registry
        #: multi-model serving (ISSUE 20): which hosted model this
        #: controller's lifecycle belongs to.  None = the single-model
        #: surface; set, it rides every generation's telemetry as the
        #: ``model_id`` label.
        self.model_id = None if model_id is None else str(model_id)
        #: whether lifecycle transitions mutate the registry's single
        #: stable/canary stage slots.  A model-multiplexed replica hosts
        #: N versions with INDEPENDENT lifecycles — N controllers racing
        #: one registry stage pointer would churn it, so the ModelTable
        #: runs with ``track_registry=False`` (loads still come from the
        #: registry; only stage promotion/rollback bookkeeping is off).
        self.track_registry = bool(track_registry)
        self.policy = policy if policy is not None else RollbackPolicy()
        self.canary_fraction = float(canary_fraction)
        self.shadow = bool(shadow)
        self.split_seed = int(split_seed)
        self.check_every_batches = max(1, int(check_every_batches))
        self._endpoint_kw = dict(endpoint_kw)
        # the routing lock guards ONLY the pointer reads/flips (never
        # held while scoring); the deploy lock serializes the slow
        # build-and-warm path so two deploys cannot interleave
        self._route_lock = threading.Lock()
        self._deploy_lock = threading.Lock()
        self._stable: Optional[Generation] = None
        self._canary: Optional[Generation] = None
        self._gen_counter = 0
        self._batches_since_check = 0
        self._events: list[dict] = []
        self._shadow_lock = threading.Lock()
        self._shadow_stats = {
            "rows": 0, "rows_differed": 0,
            "max_abs_delta": 0.0, "sum_abs_delta": 0.0,
        }
        #: fleet view source (ISSUE 14 satellite): a zero-arg callable
        #: returning the fleet status document, or a path to the fleet
        #: controller's atomically-published ``fleet_status.json`` -
        #: ``summary_json()`` then carries per-replica generation /
        #: heartbeat age / in-flight in ONE consistent document instead
        #: of every consumer re-reading N obs shards
        self.fleet_status_source: Optional[Any] = None

    # -- lifecycle ----------------------------------------------------------
    def _event(self, event: str, **kw: Any) -> dict:
        entry = {"event": event, "t": time.time(), **kw}
        with self._route_lock:
            self._events.append(entry)
            if len(self._events) > _MAX_EVENTS:
                del self._events[0]
        # every lifecycle event is also a zero-duration span on the
        # ambient run trace (obs/): a swap/canary/rollback lines up
        # causally with the serving batches around it
        _obs_trace.tracer().event("deploy." + event, **kw)
        return entry

    def _build_generation(self, model, version: str,
                          **endpoint_kw: Any) -> tuple[Generation, float]:
        """Compile + warm a new generation entirely off-pointer."""
        kw = dict(self._endpoint_kw, **endpoint_kw)
        telemetry = kw.pop("telemetry", None) or ServingTelemetry()
        gen_id = self._gen_counter + 1
        telemetry.set_model_version(version, generation=gen_id)
        if self.model_id is not None:
            telemetry.set_model_id(self.model_id)
        t0 = time.perf_counter()
        endpoint = compile_endpoint(model, telemetry=telemetry, **kw)
        warm_s = time.perf_counter() - t0
        return Generation(
            generation=gen_id, version=version, endpoint=endpoint,
            deployed_at=time.time(),
        ), warm_s

    def deploy(self, model, version: str = "unversioned",
               **endpoint_kw: Any) -> Generation:
        """Hot-swap ``model`` in as the new stable generation.  The old
        generation keeps serving until the single pointer flip; a fault
        raised in the swap window (``registry.swap_crash``) leaves it
        serving untouched."""
        with self._deploy_lock:
            gen, warm_s = self._build_generation(model, version,
                                                 **endpoint_kw)
            # swap-crash drill: the new endpoint is built but not yet
            # published — a failure here must not disturb the old
            # generation (callers keep scoring through it)
            _faults.inject("registry.swap_crash")
            t0 = time.perf_counter()
            with self._route_lock:
                self._gen_counter = gen.generation
                old = self._stable
                self._stable = gen
            flip_us = (time.perf_counter() - t0) * 1e6
        event = self._event(
            "swap", version=version, generation=gen.generation,
            from_version=old.version if old else None,
            warm_s=round(warm_s, 4), flip_us=round(flip_us, 1),
        )
        gen.endpoint.telemetry.record_lifecycle(event)
        log.info(
            "%s generation %d (version %s) live: warmed in %.3fs, "
            "pointer flip %.1fus", LOG_PREFIX, gen.generation, version,
            warm_s, flip_us,
        )
        return gen

    def deploy_version(self, version: str, workflow,
                       **endpoint_kw: Any) -> Generation:
        """Load ``version`` from the attached registry, promote it to
        the registry's stable slot, then hot-swap it in.  The promote
        runs FIRST so an ineligible version (e.g. retired — revert via
        ``registry.rollback`` instead) fails fast with the live pointer
        and the registry both untouched; if the swap itself then fails,
        the registry already names the intended stable (desired state)
        while the old generation keeps serving — loud and retryable,
        never silently divergent."""
        if self.registry is None:
            raise RegistryError("deploy_version needs an attached registry")
        model = self.registry.load(version, workflow)
        if (self.track_registry
                and self.registry.get(version).stage != "stable"):
            self.registry.promote(version, to="stable")
        return self.deploy(model, version=version, **endpoint_kw)

    def start_canary(self, model, version: str = "candidate",
                     fraction: Optional[float] = None,
                     shadow: Optional[bool] = None,
                     **endpoint_kw: Any) -> Generation:
        """Bring a candidate up as the canary generation (hash-routed
        ``fraction`` of traffic, or shadow-scored)."""
        if fraction is not None and not (0.0 <= fraction <= 1.0):
            raise ValueError("canary fraction must be in [0, 1]")
        with self._deploy_lock:
            # preconditions BEFORE the expensive endpoint build+warm: a
            # bad fraction or an occupied slot must not cost a compile
            # or burn a generation id
            with self._route_lock:
                if self._stable is None:
                    raise RegistryError(
                        "cannot start a canary with no stable generation"
                    )
                if self._canary is not None:
                    raise RegistryError(
                        f"canary slot already held by generation "
                        f"{self._canary.generation} "
                        f"({self._canary.version})"
                    )
            gen, warm_s = self._build_generation(model, version,
                                                 **endpoint_kw)
            with self._route_lock:
                self._gen_counter = gen.generation
                if fraction is not None:
                    self.canary_fraction = float(fraction)
                if shadow is not None:
                    self.shadow = bool(shadow)
                self._canary = gen
                self._batches_since_check = 0
        event = self._event(
            "canary_start", version=version, generation=gen.generation,
            fraction=self.canary_fraction, shadow=self.shadow,
            warm_s=round(warm_s, 4),
        )
        gen.endpoint.telemetry.record_lifecycle(event)
        if self.registry is not None and self.track_registry:
            try:
                if self.registry.get(version).stage != "canary":
                    self.registry.promote(version, to="canary")
            except RegistryError as e:
                log.warning("canary %s not tracked in the registry: %s",
                            version, e)
        return gen

    def start_canary_version(self, version: str, workflow,
                             **kw: Any) -> Generation:
        if self.registry is None:
            raise RegistryError(
                "start_canary_version needs an attached registry")
        model = self.registry.load(version, workflow)
        return self.start_canary(model, version=version, **kw)

    def promote_canary(self) -> Generation:
        """The canary graduates: one pointer flip makes it stable (the
        same zero-drop discipline as deploy)."""
        with self._deploy_lock:
            with self._route_lock:
                canary = self._canary
                if canary is None:
                    raise RegistryError("no canary to promote")
                old = self._stable
                self._stable = canary
                self._canary = None
        event = self._event(
            "canary_promote", version=canary.version,
            generation=canary.generation,
            from_version=old.version if old else None,
        )
        canary.endpoint.telemetry.record_lifecycle(event)
        if self.registry is not None and self.track_registry:
            try:
                self.registry.promote(canary.version, to="stable")
            except RegistryError as e:
                log.warning("promoted canary %s not tracked in the "
                            "registry: %s", canary.version, e)
        return canary

    def rollback_canary(self, decision: Optional[RollbackDecision] = None,
                        reason: str = "manual") -> Optional[dict]:
        """Demote the canary (one pointer flip back to 100% stable);
        the decision + evidence land in lifecycle telemetry and, when a
        registry is attached, its lineage."""
        with self._route_lock:
            canary = self._canary
            if canary is None:
                return None
            self._canary = None
            stable = self._stable
        event = self._event(
            "rollback", version=canary.version,
            generation=canary.generation,
            reason=reason if decision is None else "policy",
            reasons=[dict(r) for r in decision.reasons] if decision
            else [],
            evidence=decision.evidence if decision else {},
        )
        canary.endpoint.telemetry.record_lifecycle(event)
        if stable is not None:
            stable.endpoint.telemetry.record_lifecycle(event)
        log.warning(
            "%s canary generation %d (version %s) ROLLED BACK: %s",
            LOG_PREFIX, canary.generation, canary.version,
            "; ".join(
                f"{r['signal']}={r['value']} (limit {r['threshold']})"
                for r in event["reasons"]
            ) or reason,
        )
        if self.registry is not None and self.track_registry:
            try:
                self.registry.rollback(
                    version=canary.version,
                    reason=event["reason"],
                    evidence=decision.to_json() if decision else None,
                )
            except RegistryError as e:
                log.warning("rolled-back canary %s not tracked in the "
                            "registry: %s", canary.version, e)
        return event

    def release_canary(self, reason: str = "undecided") -> Optional[dict]:
        """Drop the canary arm WITHOUT a judgement: the pointer flips
        back to 100% stable like a rollback, but the registry records a
        ``release_canary`` (version back to candidate, slot freed,
        verdict undecided) instead of a rollback — the path for a
        canary whose evaluation window expired before either verdict
        (ISSUE 16: the continuous trainer's verdict timeout).  Returns
        None when there is no canary to release."""
        with self._route_lock:
            canary = self._canary
            if canary is None:
                return None
            self._canary = None
            stable = self._stable
        event = self._event(
            "canary_release", version=canary.version,
            generation=canary.generation, reason=reason,
        )
        canary.endpoint.telemetry.record_lifecycle(event)
        if stable is not None:
            stable.endpoint.telemetry.record_lifecycle(event)
        log.info(
            "%s canary generation %d (version %s) released undecided: "
            "%s", LOG_PREFIX, canary.generation, canary.version, reason,
        )
        if self.registry is not None and self.track_registry:
            try:
                self.registry.release_canary(reason=reason)
            except RegistryError as e:
                log.warning("released canary %s not tracked in the "
                            "registry: %s", canary.version, e)
        return event

    def unload(self) -> Optional[str]:
        """Drop the stable generation pointer — the eviction seam the
        multi-model weighted LRU (fleet/multimodel.py) pulls when a cold
        model's compiled executables must yield cache space.  Returns
        the version that was serving (what a later rehydrate must
        redeploy), or None when nothing was loaded.  Refuses while a
        canary is in flight: an active lifecycle pins the model.  A call
        that raced a scoring batch is safe — the batch resolved its
        generation pointer before the flip and finishes on the live
        object; only NEW calls see the unloaded state (RegistryError),
        which the ModelTable answers with a rehydrate."""
        with self._deploy_lock:
            with self._route_lock:
                if self._canary is not None:
                    raise RegistryError(
                        f"cannot unload: canary generation "
                        f"{self._canary.generation} "
                        f"({self._canary.version}) is in flight"
                    )
                stable = self._stable
                self._stable = None
        if stable is None:
            return None
        event = self._event(
            "unload", version=stable.version,
            generation=stable.generation,
        )
        stable.endpoint.telemetry.record_lifecycle(event)
        log.info(
            "%s generation %d (version %s) unloaded (executables "
            "released; rehydrate on next hit)", LOG_PREFIX,
            stable.generation, stable.version,
        )
        return stable.version

    @property
    def loaded(self) -> bool:
        """True while a stable generation is resident (serving)."""
        with self._route_lock:
            return self._stable is not None

    # -- routing + scoring --------------------------------------------------
    @property
    def stable_generation(self) -> Optional[Generation]:
        with self._route_lock:
            return self._stable

    @property
    def canary_generation(self) -> Optional[Generation]:
        with self._route_lock:
            return self._canary

    def routes_to_canary(self, record: Mapping[str, Any],
                         fraction: Optional[float] = None) -> bool:
        """The deterministic split decision for one record."""
        frac = self.canary_fraction if fraction is None else fraction
        h = murmur3_32(route_key(record).encode("utf-8"),
                       self.split_seed) % _SPLIT_BUCKETS
        return h < int(frac * _SPLIT_BUCKETS)

    def score_batch(self, records: Sequence[Mapping[str, Any]]) -> list:
        return self.score_batch_with_info(records)[0]

    def score_batch_with_info(
        self, records: Sequence[Mapping[str, Any]]
    ) -> tuple[list, dict]:
        """Score one batch through the live generations; element i of
        the results aligns with records[i] (the endpoint contract).
        ``info`` names the exact generations that scored this call —
        pointer reads happen ONCE, so a concurrent hot-swap can never
        split one batch across half-swapped state."""
        with self._route_lock:
            stable, canary = self._stable, self._canary
            fraction, shadow = self.canary_fraction, self.shadow
        if stable is None:
            raise RegistryError("no stable generation deployed")
        info: dict[str, Any] = {
            "stable_generation": stable.generation,
            "stable_version": stable.version,
            "canary_rows": 0,
        }
        if not records:
            return self._score_arm(stable, records), info
        if canary is None:
            results = self._score_arm(stable, records)
            return results, info
        info["canary_generation"] = canary.generation
        info["canary_version"] = canary.version
        if shadow:
            results = self._score_arm(stable, records)
            self._shadow_score(canary, records, results)
            info["shadow_rows"] = len(records)
            self._maybe_check()
            return results, info
        canary_idx = [
            i for i, r in enumerate(records)
            if self.routes_to_canary(r, fraction)
        ]
        canary_set = set(canary_idx)
        stable_idx = [i for i in range(len(records))
                      if i not in canary_set]
        results: list = [None] * len(records)
        if stable_idx:
            for i, res in zip(stable_idx, self._score_arm(
                    stable, [records[i] for i in stable_idx])):
                results[i] = res
        if canary_idx:
            canary_records = [records[i] for i in canary_idx]
            t_canary = time.perf_counter()
            try:
                canary_results = self._score_arm(canary, canary_records,
                                                 is_canary=True)
            except Exception as e:  # noqa: BLE001 - canary isolation
                # a canary defect (e.g. a stricter contract raising
                # SchemaDriftError) must never fail the stable-routed
                # rows that already scored: serve the canary's share on
                # STABLE instead, and charge the failure to the canary's
                # telemetry so the rollback policy sees it
                log.warning(
                    "canary arm failed a batch (%s: %s); re-scoring its "
                    "%d rows on stable", type(e).__name__, e,
                    len(canary_idx),
                )
                wall = time.perf_counter() - t_canary
                for _ in canary_idx:
                    canary.endpoint.telemetry.record_request(wall, "failed")
                canary_results = self._score_arm(stable, canary_records)
            for i, res in zip(canary_idx, canary_results):
                results[i] = res
        info["canary_rows"] = len(canary_idx)
        self._maybe_check()
        return results, info

    def __call__(self, record: Mapping[str, Any]) -> Any:
        return self.score_batch([record])[0]

    def _score_arm(self, gen: Generation,
                   records: Sequence[Mapping[str, Any]],
                   is_canary: bool = False,
                   record_requests: bool = True) -> list:
        """Score one arm's share of a batch on its generation, with
        per-row request accounting into that generation's telemetry (at
        this surface the request latency IS the arm's batch wall — the
        controller is the serve boundary here, there is no queue)."""
        t0 = time.perf_counter()
        if is_canary:
            # inside the timed window: injected canary slowness must be
            # visible to the latency-ratio signal, or the drill proves
            # nothing
            _faults.inject_sleep("canary.latency")
        results = gen.endpoint.score_batch(records)
        if is_canary and _faults.fires("canary.regression") is not None:
            # corrupt the LIVE canary output path, then apply the exact
            # guard + breaker accounting the endpoint's own NaN guard
            # uses — the rollback policy must see real signals, not a
            # synthetic flag
            _faults.poison_nonfinite(results)
            bad = CompiledEndpoint._nonfinite_rows(results)
            if bad:
                gen.endpoint.telemetry.record_nonfinite_rows(len(bad))
                gen.endpoint.breaker.record_failure()
                for i in bad:
                    results[i] = RowScoringError(
                        "non-finite canary score (NaN/Inf) refused by "
                        "the serving output guard"
                    )
        wall = time.perf_counter() - t0
        if record_requests:
            for res in results:
                if isinstance(res, RowScoringError):
                    outcome = (
                        f"shed_{res.shed_reason}" if res.shed else "failed"
                    )
                else:
                    outcome = "ok"
                gen.endpoint.telemetry.record_request(wall, outcome)
        return results

    # -- shadow scoring -----------------------------------------------------
    @staticmethod
    def _row_delta(a: Any, b: Any) -> Optional[float]:
        """Max abs difference over the float leaves two score dicts
        share; None when either row is not a score dict."""
        if not isinstance(a, dict) or not isinstance(b, dict):
            return None
        worst = 0.0
        for k, va in a.items():
            vb = b.get(k)
            if isinstance(va, dict) and isinstance(vb, dict):
                d = DeploymentController._row_delta(va, vb)
                if d is not None:
                    worst = max(worst, d)
            elif isinstance(va, float) and isinstance(vb, float):
                if math.isfinite(va) and math.isfinite(vb):
                    worst = max(worst, abs(va - vb))
                else:
                    worst = max(worst, float("inf"))
        return worst

    def _shadow_score(self, canary: Generation,
                      records: Sequence[Mapping[str, Any]],
                      stable_results: list) -> None:
        """Run the candidate beside stable and record output deltas;
        responses are untouched and a shadow failure must never take
        the serve path down."""
        try:
            shadow_results = self._score_arm(canary, records,
                                             is_canary=True)
        except Exception as e:  # noqa: BLE001 - shadow only
            log.warning("shadow scoring failed for a batch: %s", e)
            return
        with self._shadow_lock:
            for sr, cr in zip(stable_results, shadow_results):
                d = self._row_delta(sr, cr)
                if d is None:
                    continue
                self._shadow_stats["rows"] += 1
                if d > 1e-9:
                    self._shadow_stats["rows_differed"] += 1
                if math.isfinite(d):
                    self._shadow_stats["max_abs_delta"] = max(
                        self._shadow_stats["max_abs_delta"], d)
                    self._shadow_stats["sum_abs_delta"] += d
                else:
                    self._shadow_stats["max_abs_delta"] = float("inf")

    def shadow_stats(self) -> dict:
        with self._shadow_lock:
            stats = dict(self._shadow_stats)
        total_delta = stats.pop("sum_abs_delta")
        n = stats.get("rows", 0)
        stats["mean_abs_delta"] = (
            round(total_delta / n, 9) if n else 0.0
        )
        if not math.isfinite(stats["max_abs_delta"]):
            stats["max_abs_delta"] = None  # NaN/Inf delta: not valid JSON
        return stats

    # -- the control loop ---------------------------------------------------
    def _maybe_check(self) -> None:
        with self._route_lock:
            if self._canary is None:
                return
            self._batches_since_check += 1
            if self._batches_since_check < self.check_every_batches:
                return
            self._batches_since_check = 0
        self.check_canary()

    def check_canary(self) -> Optional[RollbackDecision]:
        """Evaluate the rollback policy against live telemetry; a
        breach demotes the canary immediately."""
        with self._route_lock:
            stable, canary = self._stable, self._canary
        if stable is None or canary is None:
            return None
        decision = self.policy.evaluate(
            stable.endpoint.telemetry.snapshot(),
            canary.endpoint.telemetry.snapshot(),
        )
        if decision.rollback:
            self.rollback_canary(decision)
        return decision

    # -- reporting ----------------------------------------------------------
    def events(self) -> list[dict]:
        with self._route_lock:
            return [dict(e) for e in self._events]

    def summary_json(self) -> dict:
        """The deployment control surface's own summary (the registry
        sibling of OpWorkflowModel.summary_json): live generations with
        their telemetry, the lifecycle event log (swaps, canary starts,
        rollback decisions + evidence), and shadow deltas."""
        with self._route_lock:
            stable, canary = self._stable, self._canary
        out = {
            "stable": stable.snapshot() if stable else None,
            "canary": canary.snapshot() if canary else None,
            "canary_fraction": self.canary_fraction,
            "shadow": self.shadow,
            "events": self.events(),
            "shadow_stats": self.shadow_stats(),
        }
        # SLO-aware deployments (policy.slo_engine, ISSUE 11) carry the
        # burn-rate state in the deploy summary: a rollback decision's
        # "why" must be readable next to the lifecycle event it caused
        eng = getattr(self.policy, "slo_engine", None)
        if eng is not None:
            try:
                out["slo"] = eng.report()
            except Exception as e:  # noqa: BLE001 - summary only
                log.warning("deploy summary: SLO report failed: %s", e)
        src = self.fleet_status_source
        if src is not None:
            # the fleet view (ISSUE 14): per-replica generation, last
            # heartbeat age, in-flight - one consistent document, read
            # torn-safe (the publisher may be replacing it right now)
            try:
                if callable(src):
                    out["fleet"] = src()
                else:
                    from ..obs.fleet import read_json_torn_safe

                    out["fleet"] = read_json_torn_safe(str(src))
            except Exception as e:  # noqa: BLE001 - summary only
                log.warning("deploy summary: fleet view failed: %s", e)
        return out

    def export(self, path: str, extra: Optional[dict] = None) -> dict:
        snap = self.summary_json()
        if extra:
            snap.update(extra)
        with open(path, "w") as f:
            json.dump(snap, f, indent=1, sort_keys=True, default=str)
            f.write("\n")
        return snap
