"""Signal-driven canary rollback policy.

The closed-loop half of the registry (reference frame: TF-Serving
advances servable versions only while health checks hold; this engine
already EMITS every needed health signal — breaker transitions and
NaN-guard hits from serving/admission.py + endpoint.py, per-feature JS
drift from schema/drift.py, latency percentiles from
serving/telemetry.py — and the policy here is what finally reads them):
a :class:`RollbackPolicy` compares the canary generation's live
``ServingTelemetry`` snapshot against the stable generation's and
returns a :class:`RollbackDecision` naming every breached signal with
its value, threshold, and the evidence snapshots.

Signal classes:

* **hard** — breaker opens and NaN/Inf-guard refusals on the canary.
  These indicate a broken model/kernel, not statistical noise, so they
  trip IMMEDIATELY regardless of sample size.
* **soft** — p99 latency ratio vs stable, per-feature JS drift, and the
  failed-row ratio.  These are distributions, so they only trip after
  ``min_canary_rows`` rows have scored on the canary (a 4-row sample
  "drifts" from pure noise; a latched false rollback is worse than a
  slightly later true one — the DriftMonitor warn gate's reasoning).
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Optional

log = logging.getLogger("transmogrifai_tpu.registry")


@dataclass
class RollbackDecision:
    """One policy evaluation: breached signals + the evidence behind
    them (recorded verbatim in the registry lineage and the controller's
    ``summary_json()`` when the rollback fires)."""

    rollback: bool
    reasons: list = field(default_factory=list)
    evidence: dict = field(default_factory=dict)
    checked_at: float = field(default_factory=time.time)

    def to_json(self) -> dict:
        return {
            "rollback": self.rollback,
            "reasons": [dict(r) for r in self.reasons],
            "evidence": dict(self.evidence),
            "checked_at": self.checked_at,
        }


def _evidence_subset(snap: dict) -> dict:
    """The slice of a ServingTelemetry snapshot a rollback decision
    cites (full snapshots are big; evidence must stay readable in the
    lineage log)."""
    return {
        "rows_scored": snap.get("rows_scored"),
        "rows_failed": snap.get("rows_failed"),
        "latency_ms": snap.get("latency_ms"),
        "breaker": snap.get("breaker"),
        "drift_js_max": snap.get("data_contract", {}).get("drift_js_max"),
        "model_version": snap.get("model_version"),
        "generation": snap.get("generation"),
    }


@dataclass
class RollbackPolicy:
    """SLO thresholds for automatic canary demotion.

    ``max_breaker_opens`` / ``max_nonfinite_rows`` are hard limits (a
    single excess trips regardless of traffic volume); the latency
    ratio, drift, and failure-ratio limits wait for ``min_canary_rows``
    canary rows.  Any limit set to ``None`` disables that signal.

    ``slo_engine`` (ISSUE 11) plugs the declarative obs-plane SLOs in
    as a third signal class: an :class:`~transmogrifai_tpu.obs.slo.
    SLOEngine` attached here is re-observed at every evaluation and any
    FIRING burn-rate alert becomes a hard rollback reason
    (``slo:<name>``) - a fleet-level objective breach demotes the
    canary even when the canary's own telemetry looks clean (e.g. the
    aggregate error budget is burning because of the traffic the canary
    sheds onto stable).  The runner's ``slo_path`` knob wires this.
    """

    min_canary_rows: int = 64
    max_breaker_opens: Optional[int] = 0
    max_nonfinite_rows: Optional[int] = 0
    max_latency_ratio: Optional[float] = 3.0
    max_drift_js: Optional[float] = 0.25
    max_failed_ratio: Optional[float] = 0.2
    slo_engine: Optional[Any] = None

    def _slo_reasons(self) -> list[dict]:
        """Firing SLO alerts as hard signals; a broken engine is
        logged, never allowed to block (or force) a rollback check."""
        if self.slo_engine is None:
            return []
        try:
            self.slo_engine.observe()
            alerts = self.slo_engine.firing()
        except Exception as e:  # noqa: BLE001 - visible, non-fatal
            log.warning("rollback policy: SLO engine failed: %s", e)
            return []
        return [
            {
                "signal": "slo:" + str(a.get("name")),
                "value": a.get("burn_short"),
                "threshold": a.get("burn_threshold"),
            }
            for a in alerts
        ]

    def evaluate(self, stable_snap: dict,
                 canary_snap: dict) -> RollbackDecision:
        """Compare live canary signals against stable; breaches become
        ``reasons`` entries of ``{signal, value, threshold}``."""
        reasons: list[dict] = list(self._slo_reasons())
        c_breaker = canary_snap.get("breaker", {})
        if (self.max_breaker_opens is not None
                and c_breaker.get("opens", 0) > self.max_breaker_opens):
            reasons.append({
                "signal": "breaker_opens",
                "value": c_breaker.get("opens", 0),
                "threshold": self.max_breaker_opens,
            })
        if (self.max_nonfinite_rows is not None
                and c_breaker.get("rows_nonfinite", 0)
                > self.max_nonfinite_rows):
            reasons.append({
                "signal": "nonfinite_rows",
                "value": c_breaker.get("rows_nonfinite", 0),
                "threshold": self.max_nonfinite_rows,
            })
        c_rows = (canary_snap.get("rows_scored", 0)
                  + canary_snap.get("rows_failed", 0))
        if c_rows >= self.min_canary_rows:
            s_p99 = (stable_snap.get("latency_ms") or {}).get("p99")
            c_p99 = (canary_snap.get("latency_ms") or {}).get("p99")
            if (self.max_latency_ratio is not None
                    and s_p99 and c_p99 and s_p99 > 0
                    and c_p99 / s_p99 > self.max_latency_ratio):
                reasons.append({
                    "signal": "p99_latency_ratio",
                    "value": round(c_p99 / s_p99, 3),
                    "threshold": self.max_latency_ratio,
                })
            drift = canary_snap.get(
                "data_contract", {}).get("drift_js_max", 0.0)
            if (self.max_drift_js is not None and drift is not None
                    and drift > self.max_drift_js):
                reasons.append({
                    "signal": "drift_js_max",
                    "value": drift,
                    "threshold": self.max_drift_js,
                })
            if (self.max_failed_ratio is not None
                    and canary_snap.get("rows_failed", 0) / c_rows
                    > self.max_failed_ratio):
                reasons.append({
                    "signal": "failed_ratio",
                    "value": round(
                        canary_snap.get("rows_failed", 0) / c_rows, 4),
                    "threshold": self.max_failed_ratio,
                })
        evidence = {
            "stable": _evidence_subset(stable_snap),
            "canary": _evidence_subset(canary_snap),
        }
        if self.slo_engine is not None:
            try:
                slo_rep = self.slo_engine.report()
                evidence["slo"] = {
                    "firing": slo_rep.get("firing"),
                    "objectives": slo_rep.get("objectives"),
                }
            except Exception as e:  # noqa: BLE001 - evidence only
                log.warning(
                    "rollback policy: SLO report failed: %s", e)
        return RollbackDecision(
            rollback=bool(reasons),
            reasons=reasons,
            evidence=evidence,
        )
