"""Refit governor: when does a drift signal become a refit decision?

The PR-4 :class:`~transmogrifai_tpu.schema.drift.DriftMonitor` produces
a per-window JS-divergence score; this module owns the POLICY that
turns scores into exactly one of four window verdicts — never a human
(ISSUE 16).  Two dampers keep the loop from thrashing:

* **hysteresis** — a single over-threshold window is routinely sampling
  noise (the monitor's own min_warn_rows rationale); only
  ``consecutive`` windows over the threshold IN A ROW trip a refit.
  Any clear window resets the streak.
* **cooldown** — right after a trigger, the next ``cooldown`` windows
  cannot trigger again no matter what they score: the freshly-refit
  model's canary is still being judged, and the windows feeding the
  governor were scored against the OLD contract anyway.  Over-threshold
  windows inside the cooldown are counted as ``suppressed`` (surfaced
  in the ``continuous`` metrics view) rather than silently dropped.

``forced=True`` models an operator- or fault-forced trigger
(``drift.false_positive``): it bypasses the hysteresis streak but NOT
the cooldown — a forced trigger during cooldown is suppressed like any
other, which is exactly the containment the false-positive drill pins.
"""
from __future__ import annotations

#: the four window verdicts observe_window can return
VERDICTS = ("clear", "over", "trigger", "suppressed")


class RefitGovernor:
    """Hysteresis + cooldown state machine over per-window drift
    scores.  Single-threaded by design: one governor per trainer, fed
    from the trainer's own cycle loop."""

    def __init__(self, threshold: float = 0.1, consecutive: int = 3,
                 cooldown: int = 2) -> None:
        if consecutive < 1:
            raise ValueError("consecutive must be >= 1")
        if cooldown < 0:
            raise ValueError("cooldown must be >= 0")
        self.threshold = float(threshold)
        self.consecutive = int(consecutive)
        self.cooldown = int(cooldown)
        self.over_streak = 0
        self.cooldown_left = 0
        self.windows = 0
        self.triggers = 0
        self.suppressed = 0

    def observe_window(self, max_js: float,
                       forced: bool = False) -> str:
        """Fold one window's worst per-feature JS score (and the forced
        flag) into the state machine; returns the window verdict."""
        self.windows += 1
        over = forced or max_js > self.threshold
        if self.cooldown_left > 0:
            self.cooldown_left -= 1
            if over:
                self.suppressed += 1
                return "suppressed"
            return "clear"
        if not over:
            self.over_streak = 0
            return "clear"
        self.over_streak += 1
        if not forced and self.over_streak < self.consecutive:
            return "over"
        self.over_streak = 0
        self.triggers += 1
        self.cooldown_left = self.cooldown
        return "trigger"

    def snapshot(self) -> dict:
        return {
            "threshold": self.threshold,
            "consecutive": self.consecutive,
            "cooldown": self.cooldown,
            "over_streak": self.over_streak,
            "cooldown_left": self.cooldown_left,
            "windows": self.windows,
            "triggers": self.triggers,
            "suppressed": self.suppressed,
        }
