"""transmogrifai_tpu.continuous: the self-operating training loop.

ISSUE 16 — a drift-triggered refit controller that closes the
data→drift→refit→canary→promote loop the earlier PRs built piecewise:
the PR-8 pipelined reader grows a follow/tail mode, the PR-4 drift
monitor a windowed reset seam, the PR-15 fused-train cache keeps refits
warm, and the PR-14 fleet plus PR-9 SLO engine judge the canary — with
no human anywhere in the cycle.  See :mod:`.trainer` for the state
machine and ``docs/continuous.md`` for the operator story.
"""
from __future__ import annotations

from .governor import RefitGovernor
from .trainer import (
    STATUS_FILENAME,
    ContinuousError,
    ContinuousTrainer,
)

__all__ = [
    "STATUS_FILENAME",
    "ContinuousError",
    "ContinuousTrainer",
    "RefitGovernor",
]
