"""ContinuousTrainer: the data→drift→refit→canary→promote loop, closed.

The first subsystem that makes the system operate itself (ISSUE 16).
Every building block exists in earlier PRs; this daemon joins them,
driving each STRICTLY through its public seams (the tests/test_style.py
``continuous`` AST gate pins that):

* **tail** — a :class:`~..readers.pipeline.ShardDirectoryFollower`
  watches a shard directory and feeds each poll's new files through the
  PR-8 interleave/prefetch pipeline (``pipelined_columns``), so a
  window's ingest is the same parallel read a batch run gets.
* **detect** — each window of rows is scored by the PR-4
  :class:`~..schema.drift.DriftMonitor` against the CURRENT stable
  model's training contract, ``reset()`` at every window boundary
  (windowed, not cumulative — the dilution bias reset() documents), and
  the per-window worst JS feeds the :class:`~.governor.RefitGovernor`
  hysteresis/cooldown machine.  A refit is a GOVERNOR decision, never a
  human's.
* **refit, warm** — a fresh workflow from the factory retrains on the
  bounded buffer of most-recent rows with the PR-15 fused-train knobs
  installed: a long-lived daemon's repeat refits hit the in-process
  program registry (``cache: memory``), and a restarted daemon's first
  refit REHYDRATES executables from ``train_xla_cache/`` (``cache:
  hit``, ``load_ms`` > 0, ``compile_ms`` == 0) instead of paying the
  cold trace+compile.
* **publish + canary** — the new version goes through
  :class:`~..registry.store.ModelRegistry`; with a fleet attached the
  PR-14 :class:`~..fleet.controller.FleetController` runs
  canary→shadow-score→auto-promote-or-rollback, the PR-9 SLO engine
  wired into ``check_canary`` as the rollback signal, and a canary
  whose verdict window expires undecided is RELEASED (slot freed, no
  judgement) rather than rolled back.  Without a fleet the publish
  promotes directly (the batch ``continuous`` run type).
* **observe** — every cycle runs under ONE ``continuous.cycle`` trace
  id (detect / refit / publish / canary / verdict child spans), a
  ``continuous`` metrics view rides the obs scrape
  (``tx_continuous_*``), and ``continuous_status.json`` is published
  atomically (tempfile + replace, the ``fleet_status.json``
  discipline) for ``tx continuous status``.

Fault points (armed in the chaos-composition schedule):

* ``continuous.refit_crash`` — hard kill in the window between refit
  completion and registry publish: the fleet must keep serving the old
  stable, and the NEXT cycle (a fresh daemon re-polling the same
  shards) must recover end-to-end.
* ``drift.false_positive`` — forces a trigger on a healthy window: the
  healthy canary must auto-promote (or cleanly release the slot),
  proving a spurious detection cannot wedge or degrade the fleet.
"""
from __future__ import annotations

import json
import logging
import os
import tempfile
import time
from collections import deque
from typing import Any, Callable, Optional, Union

from ..faults import injection as _faults
from ..obs import trace as _obs_trace
from ..obs.metrics import metrics_registry, process_instance
from ..readers.pipeline import ShardDirectoryFollower, pipelined_columns
from ..registry.store import ModelRegistry
from ..schema.drift import DriftMonitor
from .governor import RefitGovernor

log = logging.getLogger("transmogrifai_tpu.continuous")

#: the atomically-published status document, next to the watch dir (or
#: wherever ``status_dir`` points) — ``tx continuous status`` reads it
STATUS_FILENAME = "continuous_status.json"


class ContinuousError(RuntimeError):
    """The continuous loop cannot run as configured (no stable model to
    supersede and bootstrap disabled, factory broken, ...)."""


class ContinuousTrainer:
    """Drift-triggered refit controller over one watched shard dir.

    ``registry`` is a :class:`ModelRegistry` or its root path;
    ``workflow_factory`` is a zero-arg callable returning a FRESH
    workflow (or a tuple whose first element is one), or an importable
    ``module:function`` spec — the same contract fleet replica workers
    use, so the daemon, the workers and the seed trainer all rebuild
    the identical workflow.  ``fleet`` is an optional started
    :class:`~..fleet.controller.FleetController`; without one, promote
    is a direct registry pointer flip."""

    def __init__(
        self,
        watch_dir: str,
        registry: Union[ModelRegistry, str],
        workflow_factory: Union[Callable[[], Any], str],
        *,
        fleet=None,
        status_dir: Optional[str] = None,
        drift_threshold: float = 0.1,
        consecutive_windows: int = 3,
        cooldown_windows: int = 2,
        min_window_rows: int = 64,
        refit_rows: int = 4096,
        train_fused: Optional[bool] = None,
        train_cache_dir: Optional[str] = None,
        canary_fraction: float = 0.5,
        canary_min_rows: int = 48,
        canary_timeout_s: float = 90.0,
        canary_poll_s: float = 0.1,
        pipeline_workers: int = 2,
        settle_s: float = 0.0,
        bootstrap: bool = False,
    ) -> None:
        if isinstance(workflow_factory, str):
            from ..fleet.worker import load_workflow_factory

            workflow_factory = load_workflow_factory(workflow_factory)
        self.workflow_factory = workflow_factory
        self.registry = (registry if isinstance(registry, ModelRegistry)
                         else ModelRegistry(str(registry)))
        self.watch_dir = str(watch_dir)
        self.follower = ShardDirectoryFollower(self.watch_dir,
                                               settle_s=settle_s)
        self.fleet = fleet
        self.status_dir = str(status_dir) if status_dir else None
        self.drift_threshold = float(drift_threshold)
        self.min_window_rows = int(min_window_rows)
        self.refit_rows = int(refit_rows)
        self.train_fused = train_fused
        self.train_cache_dir = (str(train_cache_dir)
                                if train_cache_dir else None)
        self.canary_fraction = float(canary_fraction)
        self.canary_min_rows = int(canary_min_rows)
        self.canary_timeout_s = float(canary_timeout_s)
        self.canary_poll_s = max(float(canary_poll_s), 0.01)
        self.pipeline_workers = int(pipeline_workers)
        # bounded most-recent-rows refit buffer: a refit trains on the
        # freshest refit_rows rows the tail has seen, nothing older
        self._buffer: deque = deque(maxlen=self.refit_rows)
        self.governor = RefitGovernor(
            threshold=self.drift_threshold,
            consecutive=consecutive_windows,
            cooldown=cooldown_windows,
        )
        self.instance = process_instance()
        # counters (the `continuous` metrics view)
        self.cycles = 0
        self.refits = 0
        self.promotes = 0
        self.rollbacks = 0
        self.releases = 0
        self.forced_triggers = 0
        self.rows_ingested = 0
        self.last_max_js = 0.0
        self.refit_cache = {"hits": 0, "misses": 0, "stale": 0,
                            "memory": 0}
        self.last_refit: Optional[dict] = None
        self.last_cycle: Optional[dict] = None
        self.last_trace: Optional[str] = None
        # baseline: the CURRENT stable model's training contract
        self.version = self.registry.stable
        if self.version is None:
            if not bootstrap:
                raise ContinuousError(
                    f"registry {self.registry.root} has no stable "
                    "version to supersede (pass bootstrap=True to "
                    "train + publish one from the factory workflow)")
            with _obs_trace.span("continuous.bootstrap"):
                model = self._fresh_workflow().train()
                entry = self.registry.publish(model, stage="stable")
            self.version = entry.version
            self.model = model
        else:
            self.model = self.registry.load_stable(
                self._fresh_workflow())
        self._raw_features = tuple(self._fresh_workflow().raw_features)
        self.monitor = self._monitor_for(self.model)
        metrics_registry().register_view("continuous", self)
        self.publish_status()

    # -- plumbing -----------------------------------------------------------
    def _fresh_workflow(self):
        built = self.workflow_factory()
        return built[0] if isinstance(built, tuple) else built

    def _monitor_for(self, model) -> DriftMonitor:
        contract = getattr(model, "schema_contract", None)
        if contract is None:
            raise ContinuousError(
                "stable model carries no schema contract - drift "
                "detection needs the training distributions (train "
                "with parameters(schema_contract=True), the default)")
        if not contract.distributions:
            log.warning("stable model's contract has no captured "
                        "distributions: drift can never trigger")
        return DriftMonitor(contract,
                            warn_threshold=self.drift_threshold)

    def _adopt(self, model, version: str) -> None:
        """The promoted refit becomes the drift baseline: subsequent
        windows score against ITS training contract."""
        self.model = model
        self.version = version
        self.monitor = self._monitor_for(model)

    # -- ingest -------------------------------------------------------------
    def _ingest(self, specs) -> list:
        """One poll's shards → row records, through the PR-8 pipeline
        (interleaved parse + prefetch), in deterministic shard order."""
        schema = {f.name: f.ftype for f in self._raw_features}
        pipe = self.follower.pipeline(
            specs, schema, workers=self.pipeline_workers)
        if pipe is None:
            return []
        cols = {name: col.to_list()
                for name, col in pipelined_columns(pipe).items()}
        names = list(cols)
        n = len(cols[names[0]]) if names else 0
        return [{k: cols[k][i] for k in names} for i in range(n)]

    # -- refit --------------------------------------------------------------
    def _refit(self) -> tuple:
        """Retrain a fresh factory workflow on the buffered recent rows
        with the PR-15 fused-train knobs installed; returns (model,
        train_fused trail, rows trained on)."""
        from ..workflow.runner import train_fused_summary

        rows = list(self._buffer)
        wf = self._fresh_workflow()
        names = [f.name for f in self._raw_features]
        wf.set_input_dataset(
            {name: [r.get(name) for r in rows] for name in names})
        validators = self._install_train_fused(wf)
        model = wf.train()
        trail = train_fused_summary(validators)
        return model, trail, len(rows)

    def _install_train_fused(self, wf) -> list:
        from ..workflow.dag import compute_dag

        validators = []
        for layer in compute_dag(wf.result_features):
            for stage in layer:
                if getattr(stage, "is_model_selector", False):
                    v = stage.validator
                    if self.train_fused is not None:
                        v.train_fused = bool(self.train_fused)
                    if self.train_cache_dir:
                        v.train_cache_dir = self.train_cache_dir
                    validators.append(v)
        return validators

    def _fold_refit_trail(self, trail: Optional[dict]) -> None:
        self.last_refit = trail
        if not trail:
            return
        for key in ("hits", "misses", "stale"):
            self.refit_cache[key] += int(
                trail.get("cache", {}).get(key, 0))
        self.refit_cache["memory"] += sum(
            1 for fam in trail.get("families", {}).values()
            if fam.get("cache") == "memory")

    # -- one cycle ----------------------------------------------------------
    def run_cycle(self) -> dict:
        """Poll → window-score → (maybe) refit → publish → canary →
        verdict, the whole cycle under ONE trace id.  Returns the cycle
        document (also kept as ``last_cycle`` and folded into the
        status file)."""
        self.cycles += 1
        cycle: dict = {"cycle": self.cycles, "verdict": "idle",
                       "rows": 0, "shards": 0, "outcome": None}
        with _obs_trace.span("continuous.cycle",
                             cycle=self.cycles) as root:
            cycle["trace"] = root.trace_id
            self.last_trace = root.trace_id
            verdict, forced = self._detect(cycle)
            if verdict == "trigger":
                self.refits += 1
                with _obs_trace.span(
                        "continuous.refit",
                        trigger_js=cycle.get("max_js"),
                        forced=forced) as sp:
                    model, trail, train_rows = self._refit()
                    self._fold_refit_trail(trail)
                    cycle["refit"] = {"rows": train_rows,
                                      "train_fused": trail}
                    sp.set_attr("rows", train_rows)
                # THE crash window the refit_crash drill kills in: the
                # refit exists only in this process; the registry (and
                # therefore the fleet) must be unaffected by dying here
                _faults.inject_kill("continuous.refit_crash")
                with _obs_trace.span("continuous.publish") as sp:
                    entry = self.registry.publish(model, metrics={
                        "trigger": "continuous",
                        "max_js": cycle.get("max_js"),
                        "forced": forced,
                    })
                    sp.set_attr("version", entry.version)
                cycle["published"] = entry.version
                cycle["outcome"] = self._rollout(
                    entry.version, model, cycle)
        self.last_cycle = cycle
        self.publish_status()
        return cycle

    def _detect(self, cycle: dict) -> tuple:
        """The detect phase: ingest new shards, score the window
        against the stable contract, ask the governor."""
        with _obs_trace.span("continuous.detect") as sp:
            specs = self.follower.poll()
            records = self._ingest(specs) if specs else []
            n = len(records)
            cycle["rows"] = n
            cycle["shards"] = len(specs)
            self.rows_ingested += n
            if records:
                self._buffer.extend(records)
            forced = _faults.fires("drift.false_positive") is not None
            if forced:
                self.forced_triggers += 1
            max_js = 0.0
            if records:
                self.monitor.reset()
                self.monitor.observe(records)
                scores = self.monitor.scores()
                max_js = max(scores.values(), default=0.0)
                self.last_max_js = max_js
                cycle["scores"] = scores
            if not records and not forced:
                verdict = "idle"
            elif n < self.min_window_rows and not forced:
                # an under-filled window judges NOTHING: too few rows
                # to trust the score, too few to call the stream clear
                verdict = "thin"
            else:
                verdict = self.governor.observe_window(max_js,
                                                       forced=forced)
            cycle["verdict"] = verdict
            cycle["max_js"] = round(max_js, 6)
            cycle["forced"] = forced
            sp.set_attr("verdict", verdict)
            sp.set_attr("rows", n)
            sp.set_attr("max_js", round(max_js, 6))
        return verdict, forced

    def _rollout(self, version: str, model, cycle: dict) -> str:
        """Publish → promote hand-off.  Fleet mode: canary at
        ``canary_fraction``, poll merged telemetry until the policy
        rolls back, ``canary_min_rows`` canary rows auto-promote, or
        the verdict window expires and the slot is released undecided.
        Direct mode: stable pointer flip."""
        if self.fleet is None:
            with _obs_trace.span("continuous.verdict", version=version,
                                 mode="direct"):
                self.registry.promote(version, to="stable")
                self.promotes += 1
                self._adopt(model, version)
            return "promote"
        outcome: Optional[str] = None
        decision = None
        canary_rows = 0
        with _obs_trace.span("continuous.canary", version=version,
                             fraction=self.canary_fraction) as sp:
            self.fleet.start_canary(version,
                                    fraction=self.canary_fraction)
            deadline = time.monotonic() + self.canary_timeout_s
            while time.monotonic() < deadline:
                decision = self.fleet.check_canary()
                if decision is not None and decision.rollback:
                    outcome = "rollback"
                    break
                tel = self.fleet.canary_telemetry()
                canary_rows = int(
                    tel.get("canary", {}).get("rows_scored") or 0)
                if canary_rows >= self.canary_min_rows:
                    outcome = "promote"
                    break
                time.sleep(self.canary_poll_s)  # bounded poll quantum
            sp.set_attr("rows", canary_rows)
            sp.set_attr("outcome", outcome or "timeout")
        cycle["canary_rows"] = canary_rows
        with _obs_trace.span("continuous.verdict",
                             version=version) as sp:
            if outcome == "promote":
                self.fleet.promote_canary()
                self.promotes += 1
                self._adopt(model, version)
            elif outcome == "rollback":
                # check_canary already rolled the fleet back; the old
                # baseline stays the drift reference
                self.rollbacks += 1
                cycle["rollback_reasons"] = [
                    dict(r) for r in decision.reasons]
            else:
                outcome = "release"
                self.fleet.release_canary(
                    reason="continuous: canary verdict window "
                           "expired undecided")
                self.releases += 1
            sp.set_attr("outcome", outcome)
        return outcome

    # -- daemon loop --------------------------------------------------------
    def run(self, max_cycles: Optional[int] = None,
            idle_exit: Optional[int] = None,
            poll_interval_s: float = 0.5,
            deadline_s: Optional[float] = None) -> list:
        """Run cycles until ``max_cycles``, ``idle_exit`` consecutive
        empty polls, or ``deadline_s`` wall seconds — all optional; a
        true daemon passes none of them and runs forever.  Returns the
        cycle documents."""
        out = []
        idle = 0
        t0 = time.monotonic()
        while True:
            cycle = self.run_cycle()
            out.append(cycle)
            idle = idle + 1 if cycle["rows"] == 0 else 0
            if max_cycles is not None and len(out) >= max_cycles:
                break
            if idle_exit is not None and idle >= idle_exit:
                break
            if (deadline_s is not None
                    and time.monotonic() - t0 >= deadline_s):
                break
            time.sleep(max(float(poll_interval_s), 0.01))
        return out

    # -- observability ------------------------------------------------------
    def snapshot(self) -> dict:
        """The ``continuous`` metrics view (flat numeric leaves →
        ``tx_continuous_*`` gauges in the Prometheus scrape)."""
        return {
            "cycles": self.cycles,
            "windows": self.governor.windows,
            "refits": self.refits,
            "promotes": self.promotes,
            "rollbacks": self.rollbacks,
            "releases": self.releases,
            "suppressed_triggers": self.governor.suppressed,
            "forced_triggers": self.forced_triggers,
            "rows_ingested": self.rows_ingested,
            "shards_seen": self.follower.shards_seen,
            "buffer_rows": len(self._buffer),
            "last_max_js": self.last_max_js,
            "refit_cache_hits": self.refit_cache["hits"],
            "refit_cache_misses": self.refit_cache["misses"],
            "refit_cache_stale": self.refit_cache["stale"],
            "refit_cache_memory": self.refit_cache["memory"],
        }

    def status(self) -> dict:
        """The one consistent continuous-loop document (counters +
        governor state + last cycle) — what ``tx continuous status``
        renders and ``continuous_status.json`` persists."""
        return {
            "t": time.time(),
            "instance": self.instance,
            "watch_dir": self.watch_dir,
            "registry_root": self.registry.root,
            "mode": "fleet" if self.fleet is not None else "direct",
            "stable_version": self.version,
            "registry_stable": self.registry.stable,
            "counters": self.snapshot(),
            "governor": self.governor.snapshot(),
            "last_cycle": self.last_cycle,
            "last_trace": self.last_trace,
        }

    def publish_status(self) -> Optional[str]:
        """Atomically publish ``continuous_status.json`` (tempfile +
        replace, the fleet_status.json discipline: a reader sees a
        complete document or the previous one, never a torn one)."""
        if self.status_dir is None:
            return None
        path = os.path.join(self.status_dir, STATUS_FILENAME)
        try:
            os.makedirs(self.status_dir, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.status_dir,
                                       suffix=".tmp")
            with os.fdopen(fd, "w") as f:
                json.dump(self.status(), f, indent=1, sort_keys=True,
                          default=str)
            os.replace(tmp, path)
        except OSError as e:
            log.warning("continuous status publish failed: %s", e)
            return None
        return path
