"""Columnar data representation.

TPU-first redesign of the reference's row-object model: where the reference
stores one ``Option``-wrapped object per row per feature (reference:
features/.../types/FeatureType.scala:44), we store each feature as a whole
*column*:

* numeric-ish types  -> float32 value array + bool validity mask
* text-ish types     -> host-side object array (vectorized numpy string ops)
* vectors            -> dense float32 [n, d] + VectorMetadata provenance
* lists/sets/maps    -> host-side ragged representations
* Prediction         -> dense (prediction, rawPrediction, probability) arrays

Masks replace Option: ``mask[i] == True`` means the value is present.  All
device-bound math consumes (values, mask) pairs so null semantics survive
into jitted kernels (e.g. mean-impute must ignore masked entries, mirroring
SequenceAggregators.MeanSeqNullNum, reference: utils/.../spark/
SequenceAggregators.scala:76).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping, Optional, Sequence, Type

import numpy as np

from .feature_types import (
    FeatureType,
    Geolocation,
    OPNumeric,
    OPVector,
    Prediction,
    Real,
    Text,
)
from .vector_metadata import VectorMetadata


class Column:
    """Abstract columnar container for one feature over n rows."""

    feature_type: Type[FeatureType]

    def __len__(self) -> int:  # pragma: no cover - abstract
        raise NotImplementedError

    def take(self, indices: np.ndarray) -> "Column":  # pragma: no cover
        raise NotImplementedError

    def to_list(self) -> list:  # pragma: no cover
        raise NotImplementedError


@dataclass
class NumericColumn(Column):
    """float64 values + validity mask. Missing slots hold 0.0 (never NaN so
    kernels can sum without nan-guards); the mask is the source of truth."""

    values: np.ndarray
    mask: np.ndarray
    feature_type: Type[FeatureType] = Real

    def __post_init__(self) -> None:
        self.values = np.asarray(self.values, dtype=np.float64)
        self.mask = np.asarray(self.mask, dtype=bool)
        assert self.values.shape == self.mask.shape
        # canonicalize: masked-out slots are zero
        if not self.mask.all():
            self.values = np.where(self.mask, self.values, 0.0)

    def __len__(self) -> int:
        return len(self.values)

    def take(self, indices: np.ndarray) -> "NumericColumn":
        return NumericColumn(self.values[indices], self.mask[indices], self.feature_type)

    def to_list(self) -> list:
        return [float(v) if m else None for v, m in zip(self.values, self.mask)]

    @staticmethod
    def from_list(
        data: Iterable[Optional[float]], feature_type: Type[FeatureType] = Real
    ) -> "NumericColumn":
        vals, mask = [], []
        for x in data:
            missing = x is None or (isinstance(x, float) and np.isnan(x))
            mask.append(not missing)
            vals.append(0.0 if missing else float(x))
        return NumericColumn(np.array(vals), np.array(mask), feature_type)


@dataclass
class TextColumn(Column):
    """Host-side nullable strings (numpy object array; None = missing)."""

    values: np.ndarray
    feature_type: Type[FeatureType] = Text

    def __post_init__(self) -> None:
        self.values = np.asarray(self.values, dtype=object)

    def __len__(self) -> int:
        return len(self.values)

    @property
    def mask(self) -> np.ndarray:
        return np.array([v is not None for v in self.values], dtype=bool)

    def take(self, indices: np.ndarray) -> "TextColumn":
        return TextColumn(self.values[indices], self.feature_type)

    def to_list(self) -> list:
        return list(self.values)

    @staticmethod
    def from_list(
        data: Iterable[Optional[str]], feature_type: Type[FeatureType] = Text
    ) -> "TextColumn":
        vals = [None if v is None or v == "" else str(v) for v in data]
        return TextColumn(np.array(vals, dtype=object), feature_type)


# -- single-pass record decoding --------------------------------------------
# Columnar decode of raw record dicts, sharing the from_list missing
# semantics above.  Lives HERE (not in local/fused.py, its hot-path
# consumer) so that schema/drift.py and the serving layers can import it
# without a schema -> local layering inversion.

_NAN = float("nan")


def text_values(values: Sequence) -> list:
    """Raw values -> host list of str-or-None (TextColumn.from_list
    semantics; a plain list because downstream encodes iterate it
    element-wise anyway).  Branch order puts the common case (a
    non-empty str) on the two-check path."""
    return [
        (v or None) if type(v) is str
        else (None if v is None or v == "" else str(v))
        for v in values
    ]


def list_values(values: Sequence, as_set: bool) -> list:
    """Raw values -> tuples (order kept) or frozensets — the ONE
    textlist/datelist/multipicklist conversion shared by
    column_from_list and the fused env decode, so the two can never
    drift apart."""
    if as_set:
        return [frozenset(v) if v else frozenset() for v in values]
    return [tuple(v) if v else tuple() for v in values]


def decode_text(records: Sequence[Mapping[str, Any]], name: str):
    """Raw values -> object [n] of str-or-None (TextColumn.from_list
    semantics, shared with the fused env decode via text_values so the
    two can never diverge)."""
    return np.array(
        text_values([r.get(name) for r in records]), dtype=object
    )


def is_present_nan(v) -> bool:
    """True when a NaN-converted input is one NumericColumn.from_list
    treats as PRESENT: any non-None value that is not a python float
    NaN (a str \"nan\", an np.float32 NaN).  Present-NaN rows must keep
    NaN so the serving output guard refuses them - masking them would
    silently mean-fill junk the interpreted path rejects."""
    return v is not None and not isinstance(v, float)


def present_nan_slots(flat_idx, values) -> list:
    """Indices (of ``flat_idx``) whose ``values`` entry is a
    present-NaN input per :func:`is_present_nan`."""
    return [i for i in flat_idx if is_present_nan(values[i])]


def decode_numeric(records: Sequence[Mapping[str, Any]], name: str):
    """Raw values -> (values float64 [n], mask bool [n]) with the exact
    missing semantics of NumericColumn.from_list: None or a python
    float NaN is missing (missing slots hold 0.0, the canonical masked
    form); NaN-valued inputs of any other type stay present as NaN."""
    vals = np.array(
        [_NAN if (v := r.get(name)) is None else v for r in records],
        dtype=np.float64,
    )
    if vals.ndim != 1:  # a batch of equal-length lists would build 2D
        raise TypeError(f"feature {name!r} values are not scalars")
    mask = ~np.isnan(vals)
    if not mask.all():  # junk-NaN recovery only when NaNs exist at all
        present = [
            i for i in np.flatnonzero(~mask).tolist()
            if is_present_nan(records[i].get(name))
        ]
        mask[present] = True
    return np.where(mask, vals, 0.0), mask


@dataclass
class ListColumn(Column):
    """Ragged host-side lists (TextList/DateList/MultiPickList).  Values are
    tuples (order preserved) or frozensets for set semantics."""

    values: list
    feature_type: Type[FeatureType]

    def __len__(self) -> int:
        return len(self.values)

    def take(self, indices: np.ndarray) -> "ListColumn":
        return ListColumn([self.values[i] for i in indices], self.feature_type)

    def to_list(self) -> list:
        return [list(v) for v in self.values]


@dataclass
class GeolocationColumn(Column):
    """Dense [n, 3] (lat, lon, accuracy) + validity mask (reference:
    types/Geolocation.scala:47)."""

    values: np.ndarray
    mask: np.ndarray
    feature_type: Type[FeatureType] = Geolocation

    def __post_init__(self) -> None:
        self.values = np.asarray(self.values, dtype=np.float64).reshape(-1, 3)
        self.mask = np.asarray(self.mask, dtype=bool)
        # the reference validates at construction (Geolocation.scala:50
        # Geolocation.validate: lat in [-90, 90], lon in [-180, 180]);
        # silent (95, 200) passthrough would poison every downstream
        # distance/vectorizer computation
        if self.mask.any():
            lat = self.values[self.mask, 0]
            lon = self.values[self.mask, 1]
            bad = ~(
                (lat >= -90) & (lat <= 90) & (lon >= -180) & (lon <= 180)
            )
            if bad.any():
                rows = np.flatnonzero(self.mask)[bad][:5]
                raise ValueError(
                    "invalid geolocation coordinates (lat must be in "
                    "[-90, 90], lon in [-180, 180]) at rows "
                    f"{rows.tolist()}: "
                    f"{self.values[rows].tolist()}"
                )

    def __len__(self) -> int:
        return len(self.mask)

    def take(self, indices: np.ndarray) -> "GeolocationColumn":
        return GeolocationColumn(self.values[indices], self.mask[indices])

    def to_list(self) -> list:
        return [list(v) if m else None for v, m in zip(self.values, self.mask)]


@dataclass
class MapColumn(Column):
    """Host-side list of dicts (missing = empty dict).  Typed by the map's
    value type; vectorizers expand keys into columnar form at fit time."""

    values: list
    feature_type: Type[FeatureType]

    def __len__(self) -> int:
        return len(self.values)

    def take(self, indices: np.ndarray) -> "MapColumn":
        return MapColumn([self.values[i] for i in indices], self.feature_type)

    def to_list(self) -> list:
        return list(self.values)

    def all_keys(self) -> list[str]:
        keys: dict[str, None] = {}
        for d in self.values:
            for k in d:
                keys.setdefault(k)
        return sorted(keys)


@dataclass
class VectorColumn(Column):
    """Dense float32 [n, d] feature matrix chunk + provenance metadata."""

    values: np.ndarray
    metadata: VectorMetadata
    feature_type: Type[FeatureType] = OPVector

    def __post_init__(self) -> None:
        self.values = np.asarray(self.values, dtype=np.float32)
        if self.values.ndim == 1:
            self.values = self.values[:, None]
        if self.metadata.size and self.metadata.size != self.values.shape[1]:
            raise ValueError(
                f"vector width {self.values.shape[1]} != metadata size {self.metadata.size}"
            )

    def __len__(self) -> int:
        return len(self.values)

    @property
    def width(self) -> int:
        return self.values.shape[1]

    def take(self, indices: np.ndarray) -> "VectorColumn":
        return VectorColumn(self.values[indices], self.metadata)

    def to_list(self) -> list:
        return [row.tolist() for row in self.values]


@dataclass
class PredictionColumn(Column):
    """Model output: prediction [n], rawPrediction [n,k], probability [n,k]
    (reference Prediction type: types/Maps.scala:302-357)."""

    prediction: np.ndarray
    raw_prediction: Optional[np.ndarray] = None
    probability: Optional[np.ndarray] = None
    feature_type: Type[FeatureType] = Prediction

    def __post_init__(self) -> None:
        self.prediction = np.asarray(self.prediction, dtype=np.float64).reshape(-1)
        if self.raw_prediction is not None:
            self.raw_prediction = np.asarray(self.raw_prediction, dtype=np.float64)
            if self.raw_prediction.ndim == 1:
                self.raw_prediction = self.raw_prediction[:, None]
        if self.probability is not None:
            self.probability = np.asarray(self.probability, dtype=np.float64)
            if self.probability.ndim == 1:
                self.probability = self.probability[:, None]

    def __len__(self) -> int:
        return len(self.prediction)

    def take(self, indices: np.ndarray) -> "PredictionColumn":
        return PredictionColumn(
            self.prediction[indices],
            None if self.raw_prediction is None else self.raw_prediction[indices],
            None if self.probability is None else self.probability[indices],
        )

    def to_list(self) -> list:
        out = []
        for i in range(len(self)):
            d: dict[str, Any] = {Prediction.KEY_PREDICTION: float(self.prediction[i])}
            if self.raw_prediction is not None:
                for j, v in enumerate(self.raw_prediction[i]):
                    d[f"{Prediction.KEY_RAW}_{j}"] = float(v)
            if self.probability is not None:
                for j, v in enumerate(self.probability[i]):
                    d[f"{Prediction.KEY_PROB}_{j}"] = float(v)
            out.append(d)
        return out


def column_from_list(
    data: Sequence, feature_type: Type[FeatureType]
) -> Column:
    """Build the right Column variant for a feature type from python values."""
    kind = feature_type.kind
    if kind == "numeric":
        if isinstance(data, np.ndarray) and data.dtype.kind in "fiub":
            vals = np.asarray(data, np.float64)
            mask = ~np.isnan(vals)
            return NumericColumn(np.where(mask, vals, 0.0), mask,
                                 feature_type)
        return NumericColumn.from_list(data, feature_type)
    if kind == "text":
        return TextColumn.from_list(data, feature_type)
    if kind in ("textlist", "datelist"):
        return ListColumn(list_values(data, as_set=False), feature_type)
    if kind == "multipicklist":
        return ListColumn(list_values(data, as_set=True), feature_type)
    if kind == "geolocation":
        dense = np.zeros((len(data), 3))
        mask = np.zeros(len(data), dtype=bool)
        for i, v in enumerate(data):
            if v:
                dense[i] = list(v)[:3]
                mask[i] = True
        return GeolocationColumn(dense, mask)
    if kind == "map":
        return MapColumn([dict(v) if v else {} for v in data], feature_type)
    if kind == "vector":
        arr = np.asarray([list(v) for v in data], dtype=np.float32)
        return VectorColumn(arr, VectorMetadata("anonymous", tuple()))
    raise TypeError(f"cannot build column for kind {kind!r}")


def concat_columns(parts: Sequence[Column]) -> Column:
    """Row-concatenate column chunks of one feature (the streaming
    ingest hand-off: readers/pipeline.py materializes per-chunk columns
    while shards parse, then joins them here).  Supported for the
    column kinds the pipelined readers produce."""
    if not parts:
        raise ValueError("concat_columns needs at least one part")
    if len(parts) == 1:
        return parts[0]
    first = parts[0]
    if isinstance(first, NumericColumn):
        return NumericColumn(
            np.concatenate([p.values for p in parts]),
            np.concatenate([p.mask for p in parts]),
            first.feature_type,
        )
    if isinstance(first, TextColumn):
        return TextColumn(
            np.concatenate([p.values for p in parts]), first.feature_type
        )
    if isinstance(first, ListColumn):
        out: list = []
        for p in parts:
            out.extend(p.values)
        return ListColumn(out, first.feature_type)
    if isinstance(first, VectorColumn):
        return VectorColumn(
            np.concatenate([p.values for p in parts], axis=0),
            first.metadata,
        )
    raise TypeError(
        f"concat_columns does not support {type(first).__name__}"
    )
