"""Feature type system.

TPU-native re-design of the reference FeatureType hierarchy
(reference: features/src/main/scala/com/salesforce/op/features/types/FeatureType.scala:44,
Numerics.scala, Text.scala, Lists.scala, Sets.scala, Maps.scala, Geolocation.scala).

In the reference every value is an ``Option``-wrapped scalar object per row.
On TPU we keep the *type lattice* (45 types, nullability, categorical/text/
numeric traits) as lightweight Python classes used purely as static tags on
symbolic features, while the *data* lives in columnar arrays with validity
masks (see transmogrifai_tpu.types.columns).  The tags drive:

* Transmogrifier dispatch (which default vectorizer handles a feature),
* FeatureBuilder schema inference,
* runtime column validation.

Class attributes:
  ``kind``        - storage kind ('numeric' | 'text' | 'vector' | 'textlist' |
                    'datelist' | 'multipicklist' | 'geolocation' | 'map' | 'prediction')
  ``non_nullable``- mirrors the reference ``NonNullable`` trait
  ``is_categorical`` - mirrors the ``Categorical`` trait (PickList/ComboBox/...)
  ``value_type``  - for map types, the scalar type of the map's values
"""
from __future__ import annotations

from typing import Optional, Type


class FeatureType:
    """Root of the type lattice (abstract; instances are never created)."""

    kind: str = "abstract"
    non_nullable: bool = False
    is_categorical: bool = False
    value_type: Optional[Type["FeatureType"]] = None

    def __init__(self) -> None:  # pragma: no cover
        raise TypeError("FeatureType subclasses are static tags; do not instantiate")

    @classmethod
    def type_name(cls) -> str:
        return cls.__name__


# --------------------------------------------------------------------------
# Numerics (reference: types/OPNumeric.scala:39, types/Numerics.scala:40-150)
# --------------------------------------------------------------------------
class OPNumeric(FeatureType):
    kind = "numeric"


class Real(OPNumeric):
    pass


class RealNN(Real):
    non_nullable = True


class Binary(OPNumeric):
    is_categorical = True


class Integral(OPNumeric):
    pass


class Percent(Real):
    pass


class Currency(Real):
    pass


class Date(Integral):
    pass


class DateTime(Date):
    pass


# --------------------------------------------------------------------------
# Text (reference: types/Text.scala:48-301)
# --------------------------------------------------------------------------
class Text(FeatureType):
    kind = "text"


class Email(Text):
    pass


class Base64(Text):
    pass


class Phone(Text):
    pass


class ID(Text):
    pass


class URL(Text):
    pass


class TextArea(Text):
    pass


class PickList(Text):
    is_categorical = True


class ComboBox(Text):
    pass


class Country(Text):
    pass


class State(Text):
    pass


class PostalCode(Text):
    pass


class City(Text):
    pass


class Street(Text):
    pass


# --------------------------------------------------------------------------
# Collections (reference: types/OPVector.scala:41, Lists.scala, Sets.scala,
# Geolocation.scala:47)
# --------------------------------------------------------------------------
class OPCollection(FeatureType):
    kind = "collection"


class OPList(OPCollection):
    pass


class OPVector(OPCollection):
    kind = "vector"
    non_nullable = True


class TextList(OPList):
    kind = "textlist"


class DateList(OPList):
    kind = "datelist"


class DateTimeList(DateList):
    kind = "datelist"


class OPSet(OPCollection):
    pass


class MultiPickList(OPSet):
    kind = "multipicklist"
    is_categorical = True


class Geolocation(OPList):
    kind = "geolocation"


# --------------------------------------------------------------------------
# Maps (reference: types/OPMap.scala:38, types/Maps.scala:40-357)
# --------------------------------------------------------------------------
class OPMap(FeatureType):
    kind = "map"


def _map_type(name: str, value: Type[FeatureType]) -> Type[OPMap]:
    return type(name, (OPMap,), {"value_type": value, "kind": "map"})


TextMap = _map_type("TextMap", Text)
EmailMap = _map_type("EmailMap", Email)
Base64Map = _map_type("Base64Map", Base64)
PhoneMap = _map_type("PhoneMap", Phone)
IDMap = _map_type("IDMap", ID)
URLMap = _map_type("URLMap", URL)
TextAreaMap = _map_type("TextAreaMap", TextArea)
PickListMap = _map_type("PickListMap", PickList)
ComboBoxMap = _map_type("ComboBoxMap", ComboBox)
CountryMap = _map_type("CountryMap", Country)
StateMap = _map_type("StateMap", State)
PostalCodeMap = _map_type("PostalCodeMap", PostalCode)
CityMap = _map_type("CityMap", City)
StreetMap = _map_type("StreetMap", Street)
RealMap = _map_type("RealMap", Real)
IntegralMap = _map_type("IntegralMap", Integral)
BinaryMap = _map_type("BinaryMap", Binary)
CurrencyMap = _map_type("CurrencyMap", Currency)
PercentMap = _map_type("PercentMap", Percent)
DateMap = _map_type("DateMap", Date)
DateTimeMap = _map_type("DateTimeMap", DateTime)
MultiPickListMap = _map_type("MultiPickListMap", MultiPickList)
GeolocationMap = _map_type("GeolocationMap", Geolocation)


class Prediction(RealMap):
    """Model output map with reserved keys prediction/probability/rawPrediction
    (reference: types/Maps.scala:302-357).  Stored columnar as dense arrays."""

    kind = "prediction"
    non_nullable = True

    KEY_PREDICTION = "prediction"
    KEY_RAW = "rawPrediction"
    KEY_PROB = "probability"


# --------------------------------------------------------------------------
# Registry + helpers
# --------------------------------------------------------------------------
_ALL_TYPES: dict[str, Type[FeatureType]] = {}


def _register(cls: Type[FeatureType]) -> None:
    _ALL_TYPES[cls.__name__] = cls


for _cls in list(globals().values()):
    if isinstance(_cls, type) and issubclass(_cls, FeatureType):
        _register(_cls)


def feature_type_by_name(name: str) -> Type[FeatureType]:
    try:
        return _ALL_TYPES[name]
    except KeyError:
        raise KeyError(f"Unknown feature type: {name!r}") from None


def all_feature_types() -> dict[str, Type[FeatureType]]:
    return dict(_ALL_TYPES)


def is_subtype(a: Type[FeatureType], b: Type[FeatureType]) -> bool:
    return issubclass(a, b)
