"""Per-column provenance metadata for assembled feature vectors.

TPU-native counterpart of the reference's OpVectorColumnMetadata /
OpVectorMetadata (reference: features/src/main/scala/com/salesforce/op/utils/
spark/OpVectorColumnMetadata.scala and OpVectorMetadata.scala:49-66).

Every vectorizer that emits an OPVector column attaches one
:class:`VectorColumnMeta` per output dimension recording which raw feature
produced it, the categorical grouping, the indicator value for one-hot
columns, and whether the column is a null-tracking indicator.  This is the
backbone of SanityChecker <-> ModelInsights <-> LOCO interpretability, so it
is carried alongside the dense array from day one.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional, Sequence

OTHER_STRING = "OTHER"
NULL_STRING = "NullIndicatorValue"


@dataclass(frozen=True)
class VectorColumnMeta:
    """Provenance of a single dimension of a feature vector."""

    parent_feature_name: str
    parent_feature_type: str
    grouping: Optional[str] = None
    indicator_value: Optional[str] = None
    descriptor_value: Optional[str] = None
    index: int = 0

    @property
    def is_null_indicator(self) -> bool:
        return self.indicator_value == NULL_STRING

    @property
    def is_other_indicator(self) -> bool:
        return self.indicator_value == OTHER_STRING

    def column_name(self) -> str:
        parts = [self.parent_feature_name]
        if self.grouping is not None and self.grouping != self.parent_feature_name:
            parts.append(self.grouping)
        if self.indicator_value is not None:
            parts.append(self.indicator_value)
        elif self.descriptor_value is not None:
            parts.append(self.descriptor_value)
        return "_".join(parts) + f"_{self.index}"

    def pretty_name(self) -> str:
        """Human-facing name used by ModelInsights tables, e.g. sex = "female"."""
        base = self.grouping or self.parent_feature_name
        if self.indicator_value == NULL_STRING:
            return f"{base} = null"
        if self.indicator_value is not None:
            return f'{base} = "{self.indicator_value}"'
        if self.descriptor_value is not None:
            return f"{base} ({self.descriptor_value})"
        return base

    def to_json(self) -> dict:
        return {
            "parent_feature_name": self.parent_feature_name,
            "parent_feature_type": self.parent_feature_type,
            "grouping": self.grouping,
            "indicator_value": self.indicator_value,
            "descriptor_value": self.descriptor_value,
            "index": self.index,
        }

    @staticmethod
    def from_json(d: dict) -> "VectorColumnMeta":
        return VectorColumnMeta(**d)


@dataclass(frozen=True)
class VectorMetadata:
    """Metadata for a whole OPVector feature: ordered column provenance."""

    name: str
    columns: tuple[VectorColumnMeta, ...] = field(default_factory=tuple)

    @property
    def size(self) -> int:
        return len(self.columns)

    def reindexed(self) -> "VectorMetadata":
        # no-op fast path: fitted pipelines rebuild identical metadata on
        # EVERY transform call (row scoring pays ~1500 dataclass copies
        # per row without it - profiled 70 rows/s -> the dominant cost)
        if all(c.index == i for i, c in enumerate(self.columns)):
            return self
        cols = tuple(replace(c, index=i) for i, c in enumerate(self.columns))
        return VectorMetadata(self.name, cols)

    @staticmethod
    def combine(name: str, metas: Sequence["VectorMetadata"]) -> "VectorMetadata":
        """Concatenate metadata of several vectors (VectorsCombiner semantics,
        reference: core/.../impl/feature/VectorsCombiner.scala:47-82)."""
        cols: list[VectorColumnMeta] = []
        for m in metas:
            cols.extend(m.columns)
        return VectorMetadata(name, tuple(cols)).reindexed()

    def select(self, indices: Sequence[int]) -> "VectorMetadata":
        cols = tuple(self.columns[i] for i in indices)
        return VectorMetadata(self.name, cols).reindexed()

    def column_names(self) -> list[str]:
        return [c.column_name() for c in self.columns]

    def column_history(self, features: Optional[dict] = None) -> list[dict]:
        """Per-column lineage records (counterpart of OpVectorColumnHistory,
        reference: features/.../utils/spark/OpVectorColumnMetadata.scala +
        OpVectorColumnHistory): provenance fields plus, when a
        {feature_name: Feature} mapping is supplied, the origin raw
        features and stage uids that produced each column's parent."""
        out = []
        for c in self.columns:
            entry = {
                "columnName": c.column_name(),
                "parentFeatureName": c.parent_feature_name,
                "parentFeatureType": c.parent_feature_type,
                "grouping": c.grouping,
                "indicatorValue": c.indicator_value,
                "descriptorValue": c.descriptor_value,
                "index": c.index,
            }
            feat = (features or {}).get(c.parent_feature_name)
            if feat is not None and hasattr(feat, "history"):
                entry.update(feat.history())
            out.append(entry)
        return out

    def grouping_indices(self) -> dict[tuple[str, str], list[int]]:
        """Indices of indicator columns per (parent, grouping) categorical
        group - used by SanityChecker's Cramer's V contingency tables."""
        groups: dict[tuple[str, str], list[int]] = {}
        for i, c in enumerate(self.columns):
            if c.indicator_value is not None:
                key = (c.parent_feature_name, c.grouping or c.parent_feature_name)
                groups.setdefault(key, []).append(i)
        return groups

    def to_json(self) -> dict:
        return {"name": self.name, "columns": [c.to_json() for c in self.columns]}

    @staticmethod
    def from_json(d: dict) -> "VectorMetadata":
        return VectorMetadata(
            d["name"], tuple(VectorColumnMeta.from_json(c) for c in d["columns"])
        )
