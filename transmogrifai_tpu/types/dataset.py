"""In-memory columnar dataset: the unit of data flowing through a workflow.

Plays the role of the reference's Spark DataFrame at the workflow boundary
(reference: readers/.../DataReader.scala:173 generateDataFrame), but columnar
and mask-based.  Columns are keyed by feature name; all columns share row
count.  Row-subsetting (folds, splits) is a single ``take``.
"""
from __future__ import annotations

from typing import Dict, Iterable, Iterator, Mapping, Optional, Sequence, Type

import numpy as np

from .columns import Column, column_from_list
from .feature_types import FeatureType


class Dataset:
    def __init__(self, columns: Optional[Mapping[str, Column]] = None) -> None:
        self._columns: Dict[str, Column] = dict(columns or {})
        n = {len(c) for c in self._columns.values()}
        if len(n) > 1:
            raise ValueError(f"ragged dataset: row counts {sorted(n)}")

    # -- container protocol -------------------------------------------------
    def __len__(self) -> int:
        if not self._columns:
            return 0
        return len(next(iter(self._columns.values())))

    @property
    def n_rows(self) -> int:
        return len(self)

    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def __getitem__(self, name: str) -> Column:
        return self._columns[name]

    def __iter__(self) -> Iterator[str]:
        return iter(self._columns)

    def column_names(self) -> list[str]:
        return list(self._columns)

    def columns(self) -> Dict[str, Column]:
        return dict(self._columns)

    def set_column(self, name: str, col: Column,
                   validate: bool = True) -> None:
        """In-place column write for OWNED datasets (the serving hot loop:
        the functional with_column path rebuilds the dict and re-validates
        every column per stage).  validate=False skips the length check -
        callers own the no-ragged invariant and must re-check results."""
        if validate and self._columns and len(col) != len(self):
            raise ValueError(
                f"column {name!r} has {len(col)} rows, dataset has {len(self)}"
            )
        self._columns[name] = col

    # -- functional updates -------------------------------------------------
    def with_column(self, name: str, col: Column) -> "Dataset":
        if self._columns and len(col) != len(self):
            raise ValueError(
                f"column {name!r} has {len(col)} rows, dataset has {len(self)}"
            )
        cols = dict(self._columns)
        cols[name] = col
        return Dataset(cols)

    def with_columns(self, new: Mapping[str, Column]) -> "Dataset":
        ds = self
        for k, v in new.items():
            ds = ds.with_column(k, v)
        return ds

    def select(self, names: Iterable[str]) -> "Dataset":
        return Dataset({n: self._columns[n] for n in names})

    def drop(self, names: Iterable[str]) -> "Dataset":
        gone = set(names)
        return Dataset({n: c for n, c in self._columns.items() if n not in gone})

    def take(self, indices: np.ndarray) -> "Dataset":
        indices = np.asarray(indices)
        return Dataset({n: c.take(indices) for n, c in self._columns.items()})

    # -- constructors -------------------------------------------------------
    @staticmethod
    def concat(parts: Sequence["Dataset"]) -> "Dataset":
        """Row-concatenate chunk datasets sharing one column set (the
        streaming-ingest join, readers/pipeline.py).  Column order and
        names come from the first part; every part must carry the same
        columns."""
        from .columns import concat_columns

        parts = [p for p in parts if len(p)]
        if not parts:
            return Dataset()
        names = parts[0].column_names()
        for p in parts[1:]:
            if p.column_names() != names:
                raise ValueError(
                    "Dataset.concat parts disagree on columns: "
                    f"{names} vs {p.column_names()}"
                )
        return Dataset({
            n: concat_columns([p[n] for p in parts]) for n in names
        })

    @staticmethod
    def from_pylists(
        data: Mapping[str, Sequence], types: Mapping[str, Type[FeatureType]]
    ) -> "Dataset":
        return Dataset(
            {name: column_from_list(vals, types[name]) for name, vals in data.items()}
        )

    def to_pylists(self) -> dict[str, list]:
        return {n: c.to_list() for n, c in self._columns.items()}

    def __repr__(self) -> str:
        cols = ", ".join(
            f"{n}:{c.feature_type.__name__}" for n, c in self._columns.items()
        )
        return f"Dataset[{len(self)} rows]({cols})"
