"""Generalized linear regression via jitted IRLS.

Counterpart of OpGeneralizedLinearRegression (reference: core/.../impl/
regression/OpGeneralizedLinearRegression.scala wrapping Spark GLR; default
grid families gaussian/poisson - DefaultSelectorParams.DistFamily).
Canonical links: gaussian-identity, poisson-log, gamma-log (non-canonical
but standard), binomial-logit.  Same weighted-Newton shape as the logistic
kernel, so the CV fan-out batches identically.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import PredictorEstimator


@partial(jax.jit, static_argnames=("family", "iters"))
def _glm_fit_kernel(X, y, w, reg, family: str, iters: int = 25):
    """Standardization folded into the algebra (identities documented in
    logistic_regression._lr_fit_kernel): no standardized copy of X is
    materialized, so a vmap over CV fold weight vectors reads the shared
    design matrix and adds only O(d^2) per-replica state."""
    n, d = X.shape
    wsum = jnp.maximum(w.sum(), 1e-12)
    # global pre-centering + inactive-column exclusion: same f32
    # conditioning fix as logistic_regression._lr_fit_kernel
    m0 = X.mean(axis=0)
    X = X - m0
    mu_x = (w @ X) / wsum
    msq = (w @ (X * X)) / wsum
    var = msq - mu_x**2
    active = var > 1e-6 * msq + 1e-30
    sd = jnp.where(active, jnp.sqrt(jnp.maximum(var, 1e-12)), 1.0)

    ybar = (w @ y) / wsum
    if family == "poisson":
        b0_init = jnp.log(jnp.maximum(ybar, 1e-6))
    elif family == "gamma":
        b0_init = jnp.log(jnp.maximum(ybar, 1e-6))
    elif family == "binomial":
        p = jnp.clip(ybar, 1e-6, 1 - 1e-6)
        b0_init = jnp.log(p / (1 - p))
    else:
        b0_init = ybar

    def mean_and_weight(eta):
        if family == "poisson":
            mu = jnp.exp(jnp.clip(eta, -30, 30))
            return mu, mu           # var = mu, canonical log link
        if family == "gamma":
            mu = jnp.exp(jnp.clip(eta, -30, 30))
            return mu, jnp.ones_like(mu)  # log link, var ~ mu^2 -> wls w=1
        if family == "binomial":
            mu = jax.nn.sigmoid(eta)
            return mu, mu * (1 - mu)
        return eta, jnp.ones_like(eta)  # gaussian identity

    def step(carry, _):
        beta, b0 = carry  # beta in standardized space
        gamma = beta / sd
        eta = X @ gamma + (b0 - mu_x @ gamma)
        mu, wt = mean_and_weight(eta)
        wt = w * wt + 1e-8
        resid = w * (mu - y)
        sr = resid.sum()
        g = ((X.T @ resid - mu_x * sr) / sd / wsum + reg * beta) * active
        XtWX = X.T @ (X * wt[:, None])
        a = wt @ X
        s = wt.sum()
        Hs = (
            XtWX - jnp.outer(mu_x, a) - jnp.outer(a, mu_x)
            + s * jnp.outer(mu_x, mu_x)
        ) / jnp.outer(sd, sd) / wsum
        Hs = Hs * jnp.outer(active, active)
        H = Hs + jnp.diag(jnp.full((d,), reg + 1e-9) + (1.0 - active))
        g0 = sr / wsum
        h0 = s / wsum
        delta = jax.scipy.linalg.solve(H, g, assume_a="pos")
        return (beta - delta, b0 - g0 / h0), None

    (beta_s, b0), _ = jax.lax.scan(
        step, (jnp.zeros((d,)), b0_init), None, length=iters
    )
    beta = beta_s / sd
    return beta, b0 - ((mu_x + m0) * beta).sum()


@partial(jax.jit, static_argnames=("family", "iters"))
def _glm_fit_folds_kernel(X, y, W, reg, family: str, iters: int):
    return jax.vmap(
        lambda w: _glm_fit_kernel(X, y, w, reg, family, iters)
    )(W)


class OpGeneralizedLinearRegression(PredictorEstimator):
    model_type = "OpGeneralizedLinearRegression"

    def __init__(
        self, family: str = "gaussian", reg_param: float = 0.0,
        max_iter: int = 25, **kw,
    ) -> None:
        super().__init__(**kw)
        self.params.setdefault("family", family)
        self.params.setdefault("reg_param", reg_param)
        self.params.setdefault("max_iter", max_iter)

    def fit_arrays(self, X, y, w=None) -> Any:
        n = len(y)
        w = np.ones(n) if w is None else w
        beta, b0 = _glm_fit_kernel(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(float(self.params["reg_param"])),
            family=self.params["family"],
            iters=int(self.params["max_iter"]),
        )
        return {
            "beta": np.asarray(beta),
            "intercept": float(b0),
            "family": self.params["family"],
        }

    def fit_arrays_folds(self, X, y, W) -> list:
        """CV fan-out: folds ride the weight axis of one vmapped IRLS
        dispatch (no per-fold host loop)."""
        betas, b0s = _glm_fit_folds_kernel(
            jnp.asarray(X), jnp.asarray(y),
            jnp.asarray(np.asarray(W, np.float64)),
            jnp.asarray(float(self.params["reg_param"])),
            family=self.params["family"],
            iters=int(self.params["max_iter"]),
        )
        betas, b0s = np.asarray(betas), np.asarray(b0s)
        return [
            {"beta": betas[f], "intercept": float(b0s[f]),
             "family": self.params["family"]}
            for f in range(len(W))
        ]

    def predict_arrays(self, params: Any, X: np.ndarray):
        eta = X @ params["beta"] + params["intercept"]
        fam = params["family"]
        if fam in ("poisson", "gamma"):
            pred = np.exp(np.clip(eta, -30, 30))
        elif fam == "binomial":
            pred = 1.0 / (1.0 + np.exp(-eta))
        else:
            pred = eta
        return pred.astype(np.float64), None, None

    def contributions(self, params: Any) -> Optional[np.ndarray]:
        return np.abs(params["beta"])
