"""Generalized linear regression via jitted IRLS.

Counterpart of OpGeneralizedLinearRegression (reference: core/.../impl/
regression/OpGeneralizedLinearRegression.scala wrapping Spark GLR; default
grid families gaussian/poisson - DefaultSelectorParams.DistFamily).
Links: gaussian-identity, poisson-log, gamma-log (non-canonical but
standard), binomial-logit, tweedie log (default) or power link via
``link_power`` (pass link_power = 1 - variance_power to reproduce the
reference's Spark GLR default exactly).  Each family's IRLS uses the proper score
(y - mu) * (dmu/deta) / V(mu) and Fisher weight (dmu/deta)^2 / V(mu).
Same weighted-Newton shape as the logistic kernel, so the CV fan-out
batches identically.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import PredictorEstimator

_FAMILIES = ("gaussian", "poisson", "gamma", "binomial", "tweedie")


def _norm_family(fam) -> str:
    """Validate at the point of CONSUMPTION, not just construction:
    selector grids and workflow params set family via with_params()/set(),
    which bypass __init__ - a typo must raise, not silently fall through
    to the gaussian branch (review r5)."""
    f = str(fam).lower()
    if f not in _FAMILIES:
        raise ValueError(f"unknown GLM family: {fam!r}")
    return f


def _check_var_power(p: float) -> float:
    """Tweedie distributions do not exist for 0 < p < 1 (Spark GLR's
    variancePower restricts to {0} union [1, inf))."""
    p = float(p)
    if 0.0 < p < 1.0:
        raise ValueError(
            f"tweedie variance_power must be 0 or >= 1, got {p}"
        )
    return p


@partial(jax.jit, static_argnames=("family", "iters"))
def _glm_fit_kernel(X, y, w, reg, family: str, iters: int = 25,
                    var_power=1.5, link_power=0.0):
    """Standardization folded into the algebra (identities documented in
    logistic_regression._lr_fit_kernel): no standardized copy of X is
    materialized, so a vmap over CV fold weight vectors reads the shared
    design matrix and adds only O(d^2) per-replica state."""
    n, d = X.shape
    wsum = jnp.maximum(w.sum(), 1e-12)
    # global pre-centering + inactive-column exclusion: same f32
    # conditioning fix as logistic_regression._lr_fit_kernel
    m0 = X.mean(axis=0)
    X = X - m0
    mu_x = (w @ X) / wsum
    msq = (w @ (X * X)) / wsum
    var = msq - mu_x**2
    active = var > 1e-6 * msq + 1e-30
    sd = jnp.where(active, jnp.sqrt(jnp.maximum(var, 1e-12)), 1.0)

    ybar = (w @ y) / wsum
    if family == "tweedie":
        # link_power 0 = log link; else the power link eta = mu^lp
        # (Spark GLR's default tweedie link is lp = 1 - variancePower)
        b0_init = jnp.where(
            link_power == 0.0,
            jnp.log(jnp.maximum(ybar, 1e-6)),
            jnp.maximum(ybar, 1e-6) ** link_power,
        )
    elif family in ("poisson", "gamma"):
        b0_init = jnp.log(jnp.maximum(ybar, 1e-6))
    elif family == "binomial":
        p = jnp.clip(ybar, 1e-6, 1 - 1e-6)
        b0_init = jnp.log(p / (1 - p))
    else:
        b0_init = ybar

    def mean_weight_score(eta):
        """(mu, Fisher weight (dmu/deta)^2 / V, score factor so that
        resid = w * (mu - y) * factor is MINUS the eta-score).  Getting
        the factor right matters: the round-4 gamma used factor 1, whose
        fixed point is the POISSON estimating equation - coefficients
        systematically off whenever the model is not exact."""
        if family == "poisson":
            mu = jnp.exp(jnp.clip(eta, -30, 30))
            return mu, mu, jnp.ones_like(mu)  # canonical log link
        if family == "gamma":
            mu = jnp.exp(jnp.clip(eta, -30, 30))
            # log link: dmu/deta = mu, V = mu^2 -> weight 1, score /mu
            return mu, jnp.ones_like(mu), 1.0 / jnp.maximum(mu, 1e-12)
        if family == "tweedie":
            # V = mu^p.  Log link (lp=0): dmu/deta = mu -> weight
            # mu^(2-p), score mu^(1-p).  Power link eta = mu^lp:
            # dmu/deta = mu^(1-lp)/lp -> weight mu^(2-2lp-p)/lp^2,
            # score mu^(1-lp-p)/lp.  lax.cond keeps one jitted kernel.
            def _log_link(e):
                mu = jnp.exp(jnp.clip(e, -30, 30))
                ms = jnp.maximum(mu, 1e-12)
                return (mu, ms ** (2.0 - var_power),
                        ms ** (1.0 - var_power))

            def _pow_link(e):
                lp = jnp.where(link_power == 0.0, 1.0, link_power)
                # a Newton iterate can push eta out of the link's domain
                # (eta = mu^lp > 0); clamp mu to a sane range or a single
                # bad step explodes the weights into NaN (seed-42 repro)
                mu = jnp.clip(
                    jnp.maximum(e, 1e-6) ** (1.0 / lp), 1e-6, 1e8
                )
                return (
                    mu,
                    mu ** (2.0 - 2.0 * lp - var_power) / (lp * lp),
                    mu ** (1.0 - lp - var_power) / lp,
                )

            return jax.lax.cond(link_power == 0.0, _log_link, _pow_link,
                                eta)
        if family == "binomial":
            mu = jax.nn.sigmoid(eta)
            return mu, mu * (1 - mu), jnp.ones_like(mu)
        return eta, jnp.ones_like(eta), jnp.ones_like(eta)  # gaussian

    def step(carry, _):
        beta, b0 = carry  # beta in standardized space
        gamma = beta / sd
        eta = X @ gamma + (b0 - mu_x @ gamma)
        mu, wt, fac = mean_weight_score(eta)
        wt = w * wt + 1e-8
        resid = w * (mu - y) * fac
        sr = resid.sum()
        g = ((X.T @ resid - mu_x * sr) / sd / wsum + reg * beta) * active
        XtWX = X.T @ (X * wt[:, None])
        a = wt @ X
        s = wt.sum()
        Hs = (
            XtWX - jnp.outer(mu_x, a) - jnp.outer(a, mu_x)
            + s * jnp.outer(mu_x, mu_x)
        ) / jnp.outer(sd, sd) / wsum
        Hs = Hs * jnp.outer(active, active)
        # dimension-aware f32 ridge, same hardening as the LR kernels
        from .packed_newton import pd_jitter

        ridge = pd_jitter(jnp.trace(Hs) / d, d, hess_bf16=False)
        H = Hs + jnp.diag(jnp.full((d,), reg) + ridge + (1.0 - active))
        g0 = sr / wsum
        h0 = s / wsum
        delta = jax.scipy.linalg.solve(H, g, assume_a="pos")
        # a non-finite step (singular H after a domain excursion) must
        # not poison the carry - same guard as the softmax kernel
        delta = jnp.where(jnp.isfinite(delta), delta, 0.0)
        step0 = g0 / h0
        step0 = jnp.where(jnp.isfinite(step0), step0, 0.0)
        return (beta - delta, b0 - step0), None

    (beta_s, b0), _ = jax.lax.scan(
        step, (jnp.zeros((d,)), b0_init), None, length=iters
    )
    beta = beta_s / sd
    return beta, b0 - ((mu_x + m0) * beta).sum()


@partial(jax.jit, static_argnames=("family", "iters"))
def _glm_fit_folds_kernel(X, y, W, reg, family: str, iters: int,
                          var_power=1.5, link_power=0.0):
    return jax.vmap(
        lambda w: _glm_fit_kernel(X, y, w, reg, family, iters, var_power,
                                  link_power)
    )(W)


class OpGeneralizedLinearRegression(PredictorEstimator):
    #: fused serving seam: predict_arrays (numpy link fn) is pure host-side
    lowerable = True
    model_type = "OpGeneralizedLinearRegression"

    def __init__(
        self, family: str = "gaussian", reg_param: float = 0.0,
        max_iter: int = 25, variance_power: float = 1.5,
        link_power: float = 0.0, **kw,
    ) -> None:
        super().__init__(**kw)
        self.params.setdefault("family", _norm_family(family))
        self.params.setdefault("reg_param", reg_param)
        self.params.setdefault("max_iter", max_iter)
        # tweedie link: 0 = log (our default), else the power link
        # eta = mu^lp (Spark GLR defaults lp = 1 - variancePower; pass
        # link_power=1-p to reproduce it exactly)
        self.params.setdefault("link_power", float(link_power))
        # tweedie variance power (reference variancePower, used only
        # for family='tweedie')
        self.params.setdefault(
            "variance_power", _check_var_power(variance_power)
        )

    def fit_arrays(self, X, y, w=None) -> Any:
        n = len(y)
        w = np.ones(n) if w is None else w
        beta, b0 = _glm_fit_kernel(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(float(self.params["reg_param"])),
            family=_norm_family(self.params["family"]),
            iters=int(self.params["max_iter"]),
            var_power=jnp.asarray(
                _check_var_power(self.params.get("variance_power", 1.5))
            ),
            link_power=jnp.asarray(
                float(self.params.get("link_power", 0.0))
            ),
        )
        return {
            "beta": np.asarray(beta),
            "intercept": float(b0),
            "family": self.params["family"],
            "link_power": float(self.params.get("link_power", 0.0)),
        }

    def fit_arrays_folds(self, X, y, W) -> list:
        """CV fan-out: folds ride the weight axis of one vmapped IRLS
        dispatch (no per-fold host loop)."""
        betas, b0s = _glm_fit_folds_kernel(
            jnp.asarray(X), jnp.asarray(y),
            jnp.asarray(np.asarray(W, np.float64)),
            jnp.asarray(float(self.params["reg_param"])),
            family=_norm_family(self.params["family"]),
            iters=int(self.params["max_iter"]),
            var_power=jnp.asarray(
                _check_var_power(self.params.get("variance_power", 1.5))
            ),
            link_power=jnp.asarray(
                float(self.params.get("link_power", 0.0))
            ),
        )
        betas, b0s = np.asarray(betas), np.asarray(b0s)
        return [
            {"beta": betas[f], "intercept": float(b0s[f]),
             "family": self.params["family"],
             "link_power": float(self.params.get("link_power", 0.0))}
            for f in range(len(W))
        ]

    def predict_arrays(self, params: Any, X: np.ndarray):
        eta = X @ params["beta"] + params["intercept"]
        fam = _norm_family(params["family"])
        lp = float(params.get("link_power", 0.0))
        if fam == "tweedie" and lp != 0.0:
            # same domain clamp as the kernel: eta outside the power
            # link's range must not explode the mean
            pred = np.clip(
                np.maximum(eta, 1e-6) ** (1.0 / lp), 1e-6, 1e8
            )
        elif fam in ("poisson", "gamma", "tweedie"):
            pred = np.exp(np.clip(eta, -30, 30))
        elif fam == "binomial":
            pred = 1.0 / (1.0 + np.exp(-eta))
        else:
            pred = eta
        return pred.astype(np.float64), None, None

    def predict_arrays_xla(self, params: Any, X):
        """jax-traceable mirror of the numpy link-function head for the
        XLA fused backend (local/fused_xla.py)."""
        eta = X @ jnp.asarray(params["beta"]) + params["intercept"]
        fam = _norm_family(params["family"])
        lp = float(params.get("link_power", 0.0))
        if fam == "tweedie" and lp != 0.0:
            pred = jnp.clip(
                jnp.maximum(eta, 1e-6) ** (1.0 / lp), 1e-6, 1e8
            )
        elif fam in ("poisson", "gamma", "tweedie"):
            pred = jnp.exp(jnp.clip(eta, -30, 30))
        elif fam == "binomial":
            pred = 1.0 / (1.0 + jnp.exp(-eta))
        else:
            pred = eta
        return pred.astype(jnp.float64), None, None

    def contributions(self, params: Any) -> Optional[np.ndarray]:
        return np.abs(params["beta"])
