"""Multilayer perceptron classifier.

Counterpart of OpMultilayerPerceptronClassifier (reference: core/.../impl/
classification/OpMultilayerPerceptronClassifier.scala wrapping Spark MLlib
MultilayerPerceptronClassifier - layer spec, LBFGS).  TPU-native: the whole
training loop is one jitted lax.scan of Adam steps over full-batch
gradients (matmul-dominated, MXU-bound); softmax output, cross-entropy.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .base import PredictorEstimator


def _init_params(key, sizes):
    params = []
    for i in range(len(sizes) - 1):
        key, sub = jax.random.split(key)
        scale = jnp.sqrt(2.0 / sizes[i])
        params.append(
            (
                jax.random.normal(sub, (sizes[i], sizes[i + 1])) * scale,
                jnp.zeros((sizes[i + 1],)),
            )
        )
    return params


def _forward(params, X):
    h = X
    for W, b in params[:-1]:
        h = jax.nn.relu(h @ W + b)
    W, b = params[-1]
    return h @ W + b  # logits


def _mlp_fit_impl(X, onehot, w, key, sizes: tuple, steps: int, lr: float = 1e-2):
    # weighted standardization from TRAIN rows only (w=0 rows contribute
    # nothing) so CV folds never leak held-out statistics; returned so
    # scoring reproduces the same transform
    wsum = jnp.maximum(w.sum(), 1e-12)
    mu = (w @ X) / wsum
    sd = jnp.sqrt(jnp.maximum((w @ (X * X)) / wsum - mu**2, 0.0)) + 1e-8
    X = (X - mu) / sd
    params = _init_params(key, sizes)
    opt_state = [(jnp.zeros_like(W), jnp.zeros_like(b),
                  jnp.zeros_like(W), jnp.zeros_like(b)) for W, b in params]
    wsum = jnp.maximum(w.sum(), 1e-12)

    def loss_fn(params):
        logits = _forward(params, X)
        logp = jax.nn.log_softmax(logits, axis=1)
        return -(w[:, None] * onehot * logp).sum() / wsum

    def step(carry, i):
        params, opt = carry
        grads = jax.grad(loss_fn)(params)
        b1, b2, eps = 0.9, 0.999, 1e-8
        t = i + 1.0
        new_params, new_opt = [], []
        for (W, b), (gW, gb), (mW, mb, vW, vb) in zip(params, grads, opt):
            mW = b1 * mW + (1 - b1) * gW
            mb = b1 * mb + (1 - b1) * gb
            vW = b2 * vW + (1 - b2) * gW**2
            vb = b2 * vb + (1 - b2) * gb**2
            mhW = mW / (1 - b1**t)
            mhb = mb / (1 - b1**t)
            vhW = vW / (1 - b2**t)
            vhb = vb / (1 - b2**t)
            new_params.append(
                (W - lr * mhW / (jnp.sqrt(vhW) + eps),
                 b - lr * mhb / (jnp.sqrt(vhb) + eps))
            )
            new_opt.append((mW, mb, vW, vb))
        return (new_params, new_opt), None

    (params, _), _ = jax.lax.scan(
        step, (params, opt_state), jnp.arange(steps, dtype=jnp.float32)
    )
    return params, mu, sd


_mlp_fit_kernel = partial(jax.jit, static_argnames=("sizes", "steps"))(
    _mlp_fit_impl
)


@partial(jax.jit, static_argnames=("sizes", "steps"))
def _mlp_fit_folds_kernel(X, onehot, W, key, sizes: tuple, steps: int,
                          lr: float = 1e-2):
    return jax.vmap(
        lambda w: _mlp_fit_impl(X, onehot, w, key, sizes, steps, lr)
    )(W)


class OpMultilayerPerceptronClassifier(PredictorEstimator):
    """(reference defaults: layers from input->hidden(s)->classes, maxIter
    100; our hidden default mirrors the reference grids' [10,10])"""

    #: fused serving seam: predict_arrays_np is a pure numpy forward pass
    lowerable = True

    model_type = "OpMultilayerPerceptronClassifier"

    def __init__(
        self,
        hidden_layers: Sequence[int] = (10, 10),
        max_iter: int = 200,
        step_size: float = 0.01,
        seed: int = 42,
        **kw,
    ) -> None:
        super().__init__(**kw)
        self.params.setdefault("hidden_layers", tuple(hidden_layers))
        self.params.setdefault("max_iter", max_iter)
        self.params.setdefault("step_size", step_size)
        self.params.setdefault("seed", seed)

    def fit_arrays(self, X, y, w=None) -> Any:
        n, d = X.shape
        w = np.ones(n) if w is None else w
        classes = np.unique(y)
        onehot = (y[:, None] == classes[None, :]).astype(np.float32)
        sizes = (d, *self.params["hidden_layers"], len(classes))
        params, mu, sd = _mlp_fit_kernel(
            jnp.asarray(X, jnp.float32), jnp.asarray(onehot),
            jnp.asarray(w, jnp.float32),
            jax.random.PRNGKey(int(self.params["seed"])),
            sizes, int(self.params["max_iter"]),
            float(self.params["step_size"]),
        )
        return {
            "layers": [(np.asarray(W), np.asarray(b)) for W, b in params],
            "classes": classes,
            "mu": np.asarray(mu, np.float64),
            "sd": np.asarray(sd, np.float64),
        }

    def fit_arrays_folds(self, X, y, W) -> list:
        """CV fan-out: folds batch as a leading axis of the weight vector
        through one vmapped Adam scan; standardization is weighted
        per-fold inside the kernel.  The class set (output layer width) is
        the full-data label set - a static shape, matching the reference
        where the MLP layer spec fixes the output size up front."""
        n, d = X.shape
        classes = np.unique(y)
        onehot = (y[:, None] == classes[None, :]).astype(np.float32)
        sizes = (d, *self.params["hidden_layers"], len(classes))
        params_f, mus, sds = _mlp_fit_folds_kernel(
            jnp.asarray(X, jnp.float32), jnp.asarray(onehot),
            jnp.asarray(np.asarray(W, np.float32)),
            jax.random.PRNGKey(int(self.params["seed"])),
            sizes, int(self.params["max_iter"]),
            float(self.params["step_size"]),
        )
        mus, sds = np.asarray(mus, np.float64), np.asarray(sds, np.float64)
        out = []
        for f in range(len(W)):
            layers = [
                (np.asarray(Wl[f]), np.asarray(bl[f])) for Wl, bl in params_f
            ]
            out.append({"layers": layers, "classes": classes, "mu": mus[f],
                        "sd": sds[f]})
        return out

    def predict_arrays(self, params: Any, X: np.ndarray):
        Xs = jnp.asarray((X - params["mu"]) / params["sd"], jnp.float32)
        layers = [(jnp.asarray(W), jnp.asarray(b)) for W, b in params["layers"]]
        logits = np.asarray(_forward(layers, Xs), dtype=np.float64)
        return self._finalize_np(params, logits)

    def predict_arrays_np(self, params: Any, X: np.ndarray):
        h = ((X - params["mu"]) / params["sd"]).astype(np.float64)
        for W, b in params["layers"][:-1]:
            h = np.maximum(h @ W + b, 0.0)
        W, b = params["layers"][-1]
        return self._finalize_np(params, h @ W + b)

    @staticmethod
    def _finalize_np(params, logits):
        prob = np.exp(logits - logits.max(axis=1, keepdims=True))
        prob /= prob.sum(axis=1, keepdims=True)
        pred = params["classes"][prob.argmax(axis=1)].astype(np.float64)
        return pred, logits, prob

    def predict_arrays_xla(self, params: Any, X):
        """jax-traceable mirror of ``predict_arrays_np`` for the XLA
        fused backend (local/fused_xla.py): f64 relu matmul chain +
        softmax head; parity vs BLAS accumulates a few ULP per layer
        (budget pinned in tests/test_fused_xla.py)."""
        h = ((X - jnp.asarray(params["mu"]))
             / jnp.asarray(params["sd"])).astype(jnp.float64)
        for W, b in params["layers"][:-1]:
            h = jnp.maximum(h @ jnp.asarray(W) + jnp.asarray(b), 0.0)
        W, b = params["layers"][-1]
        logits = h @ jnp.asarray(W) + jnp.asarray(b)
        prob = jnp.exp(logits - logits.max(axis=1, keepdims=True))
        prob = prob / prob.sum(axis=1, keepdims=True)
        classes = jnp.asarray(np.asarray(params["classes"],
                                         dtype=np.float64))
        pred = classes[jnp.argmax(prob, axis=1)].astype(jnp.float64)
        return pred, logits, prob
