"""Naive Bayes classifier.

Counterpart of OpNaiveBayes (reference: core/.../impl/classification/
OpNaiveBayes.scala wrapping Spark MLlib multinomial NaiveBayes, smoothing
1.0).  Closed-form fit: one matmul for per-class feature sums (MXU), log
posteriors vectorized.  Multinomial over non-negative features; negative
inputs are shifted per-feature (the vectorizers emit one-hot/hashed counts,
so inputs are naturally non-negative in the transmogrified pipeline).
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .base import PredictorEstimator


def _nb_fit_impl(X, onehot, w, smoothing):
    # non-negativity shift from TRAIN rows only (w > 0): a held-out fold's
    # outlier must not move the multinomial offsets of other folds
    shift = jnp.minimum(
        jnp.where((w > 0)[:, None], X, jnp.inf).min(axis=0), 0.0
    )
    Xs = X - shift
    # per-class weighted feature sums [K, d] + class priors [K]
    cw = onehot * w[:, None]                       # [n, K]
    feat = cw.T @ Xs                               # [K, d]
    class_w = cw.sum(axis=0)                       # [K]
    theta = jnp.log(feat + smoothing) - jnp.log(
        (feat + smoothing).sum(axis=1, keepdims=True)
    )
    prior = jnp.log(class_w / jnp.maximum(class_w.sum(), 1e-12))
    return theta, prior, shift


_nb_fit_kernel = jax.jit(_nb_fit_impl)
_nb_fit_folds_kernel = jax.jit(
    jax.vmap(_nb_fit_impl, in_axes=(None, None, 0, None))
)


@jax.jit
def _nb_predict_kernel(X, theta, prior):
    raw = X @ theta.T + prior[None, :]             # [n, K] log posterior
    prob = jax.nn.softmax(raw, axis=1)
    return raw, prob


class OpNaiveBayes(PredictorEstimator):
    #: fused serving seam: predict_arrays_np is pure numpy over host params
    lowerable = True
    model_type = "OpNaiveBayes"

    def __init__(self, smoothing: float = 1.0, **kw) -> None:
        super().__init__(**kw)
        self.params.setdefault("smoothing", smoothing)

    def fit_arrays(self, X, y, w=None) -> Any:
        n, d = X.shape
        w = np.ones(n) if w is None else w
        classes = np.unique(y)
        onehot = (y[:, None] == classes[None, :]).astype(np.float64)
        theta, prior, shift = _nb_fit_kernel(
            jnp.asarray(X), jnp.asarray(onehot), jnp.asarray(w),
            jnp.asarray(float(self.params["smoothing"])),
        )
        return {
            "theta": np.asarray(theta),
            "prior": np.asarray(prior),
            "classes": classes,
            "shift": np.asarray(shift),
        }

    def fit_arrays_folds(self, X, y, W) -> list:
        """CV fan-out: the closed-form fit is one matmul, so folds batch as
        a leading axis of the weight vector in a single dispatch.  The
        non-negativity shift is per-fold (train rows only, in-kernel); the
        class set is the full-data label set, a static shape by design -
        in the reference the multinomial class count is likewise fixed by
        the label indexer, not re-derived per fold."""
        classes = np.unique(y)
        onehot = (y[:, None] == classes[None, :]).astype(np.float64)
        thetas, priors, shifts = _nb_fit_folds_kernel(
            jnp.asarray(X), jnp.asarray(onehot),
            jnp.asarray(np.asarray(W, np.float64)),
            jnp.asarray(float(self.params["smoothing"])),
        )
        thetas, priors = np.asarray(thetas), np.asarray(priors)
        shifts = np.asarray(shifts)
        return [
            {"theta": thetas[f], "prior": priors[f], "classes": classes,
             "shift": shifts[f]}
            for f in range(len(W))
        ]

    def predict_arrays(self, params: Any, X: np.ndarray):
        raw, prob = _nb_predict_kernel(
            jnp.asarray(X - params["shift"]),
            jnp.asarray(params["theta"]),
            jnp.asarray(params["prior"]),
        )
        raw, prob = np.asarray(raw, np.float64), np.asarray(prob, np.float64)
        pred = params["classes"][np.argmax(prob, axis=1)].astype(np.float64)
        return pred, raw, prob

    def predict_arrays_np(self, params: Any, X: np.ndarray):
        raw = (X - params["shift"]) @ params["theta"].T + params["prior"][None, :]
        ex = np.exp(raw - raw.max(axis=1, keepdims=True))
        prob = ex / ex.sum(axis=1, keepdims=True)
        pred = params["classes"][np.argmax(prob, axis=1)].astype(np.float64)
        return pred, raw, prob

    def predict_arrays_xla(self, params: Any, X):
        """jax-traceable mirror of the numpy head for the XLA fused
        backend (local/fused_xla.py)."""
        raw = (
            (X - jnp.asarray(params["shift"]))
            @ jnp.asarray(params["theta"]).T
            + jnp.asarray(params["prior"])[None, :]
        )
        ex = jnp.exp(raw - raw.max(axis=1, keepdims=True))
        prob = ex / ex.sum(axis=1, keepdims=True)
        classes = jnp.asarray(np.asarray(params["classes"],
                                         dtype=np.float64))
        pred = classes[jnp.argmax(prob, axis=1)].astype(jnp.float64)
        return pred, raw, prob
