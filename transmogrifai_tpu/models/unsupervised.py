"""Unsupervised text models: LDA topics and word embeddings.

Counterparts of OpLDA / OpWord2Vec (reference: core/.../impl/feature/
OpLDA.scala, OpWord2Vec.scala wrapping Spark MLlib LDA / Word2Vec).
TPU-native re-designs:

* ``OpLDA`` - batch variational EM on the dense doc-term matrix: the E-step
  is a jitted fixed-point loop of [n_docs, k] x [k, vocab] matmuls
  (MXU-bound), the M-step one matmul - no Gibbs sampling, no host loops.
* ``OpWord2Vec`` - skip-gram with negative sampling trained by a jitted
  Adam scan over precomputed (center, context, negative) index batches;
  transform averages token vectors per row (the reference's Word2Vec
  sentence embedding).
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..stages.base import Estimator, Transformer
from ..types.columns import Column, ListColumn, VectorColumn
from ..types.dataset import Dataset
from ..types.feature_types import OPVector, TextList
from ..types.vector_metadata import VectorColumnMeta, VectorMetadata


# ---------------------------------------------------------------------------
# LDA
# ---------------------------------------------------------------------------
@partial(jax.jit, static_argnames=("k", "iters", "e_steps"))
def _lda_em_kernel(counts, k: int, alpha, eta, key, iters: int = 30,
                   e_steps: int = 10):
    """Variational EM for LDA on a dense [n_docs, vocab] count matrix."""
    n, v = counts.shape
    topics = jax.random.dirichlet(key, jnp.full((v,), 1.0), (k,))  # [k, v]

    def em(topics, _):
        log_t = jnp.log(topics + 1e-12)

        def e_step(gamma, _):
            # phi ~ exp(E[log theta] + log beta); closed-ish fixed point
            e_theta = gamma / gamma.sum(axis=1, keepdims=True)  # [n, k]
            # responsibility-weighted expected counts
            weights = e_theta[:, :, None] * topics[None, :, :]  # [n, k, v]
            weights = weights / jnp.maximum(
                weights.sum(axis=1, keepdims=True), 1e-12
            )
            gamma_new = alpha + (weights * counts[:, None, :]).sum(axis=2)
            return gamma_new, None

        gamma0 = jnp.ones((n, k)) + counts.sum(axis=1, keepdims=True) / k
        gamma, _ = jax.lax.scan(e_step, gamma0, None, length=e_steps)
        e_theta = gamma / gamma.sum(axis=1, keepdims=True)
        weights = e_theta[:, :, None] * topics[None, :, :]
        weights = weights / jnp.maximum(weights.sum(axis=1, keepdims=True), 1e-12)
        new_topics = eta + (weights * counts[:, None, :]).sum(axis=0)
        new_topics = new_topics / new_topics.sum(axis=1, keepdims=True)
        return new_topics, None

    topics, _ = jax.lax.scan(em, topics, None, length=iters)
    return topics


@jax.jit
def _lda_infer_kernel(counts, topics, alpha, e_steps: int = 20):
    n = counts.shape[0]
    k = topics.shape[0]

    def e_step(gamma, _):
        e_theta = gamma / gamma.sum(axis=1, keepdims=True)
        weights = e_theta[:, :, None] * topics[None, :, :]
        weights = weights / jnp.maximum(weights.sum(axis=1, keepdims=True), 1e-12)
        gamma_new = alpha + (weights * counts[:, None, :]).sum(axis=2)
        return gamma_new, None

    gamma0 = jnp.ones((n, k)) + counts.sum(axis=1, keepdims=True) / k
    gamma, _ = jax.lax.scan(e_step, gamma0, None, length=20)
    return gamma / gamma.sum(axis=1, keepdims=True)


class OpLDAModel(Transformer):
    input_types = [OPVector]
    output_type = OPVector

    def __init__(self, topics: np.ndarray, alpha: float, **kw) -> None:
        super().__init__(**kw)
        self.topics = np.asarray(topics)
        self.alpha = alpha

    def transform_columns(self, cols: Sequence[Column], ds: Dataset) -> Column:
        (vec,) = cols
        assert isinstance(vec, VectorColumn)
        theta = np.asarray(
            _lda_infer_kernel(
                jnp.asarray(vec.values), jnp.asarray(self.topics),
                jnp.asarray(self.alpha),
            )
        )
        feat = self.input_features[0]
        meta = VectorMetadata(
            self.output_name,
            tuple(
                VectorColumnMeta(feat.name, feat.ftype.type_name(),
                                 descriptor_value=f"topic_{i}")
                for i in range(theta.shape[1])
            ),
        ).reindexed()
        return VectorColumn(theta.astype(np.float32), meta)


class OpLDA(Estimator):
    """Topic model over a term-count vector (reference: OpLDA.scala;
    k default 10, maxIter)."""

    input_types = [OPVector]
    output_type = OPVector

    def __init__(self, k: int = 10, max_iter: int = 30, alpha: float = 1.1,
                 eta: float = 1.01, seed: int = 42, **kw) -> None:
        super().__init__(**kw)
        self.k = k
        self.max_iter = max_iter
        self.alpha = alpha
        self.eta = eta
        self.seed = seed

    def fit_model(self, cols: Sequence[Column], ds: Dataset):
        (vec,) = cols
        assert isinstance(vec, VectorColumn)
        topics = _lda_em_kernel(
            jnp.asarray(vec.values), self.k,
            jnp.asarray(self.alpha), jnp.asarray(self.eta),
            jax.random.PRNGKey(self.seed), iters=self.max_iter,
        )
        return OpLDAModel(np.asarray(topics), self.alpha)


# ---------------------------------------------------------------------------
# Word2Vec (skip-gram negative sampling)
# ---------------------------------------------------------------------------
@partial(jax.jit, static_argnames=("steps", "clip_norm"))
def _w2v_sgns_kernel(centers, contexts, negatives, vocab_emb, steps: int,
                     lr: float = 0.05, clip_norm: float = 1.0):
    """One Adam-free SGD scan over precomputed index triples.

    ``clip_norm`` caps each embedding row's summed per-batch update L2
    norm.  The cap exists for the tiny-vocab regime where a token repeats
    ~batch/vocab times per batch and the summed scatter diverges; the
    caller scales it with batch size and embedding dim so legitimate
    aggregate updates at larger configs are not silently altered
    (advisor r3 finding)."""

    def step(emb, idx):
        c, ctx, neg = centers[idx], contexts[idx], negatives[idx]
        in_emb, out_emb = emb
        vc = in_emb[c]           # [b, d]
        vo = out_emb[ctx]        # [b, d]
        vn = out_emb[neg]        # [b, neg_k, d]
        pos_score = jax.nn.sigmoid((vc * vo).sum(-1))          # [b]
        neg_score = jax.nn.sigmoid((vn @ vc[:, :, None])[..., 0])  # [b, nk]
        g_pos = (pos_score - 1.0)[:, None]                     # [b, 1]
        g_neg = neg_score[..., None]                           # [b, nk, 1]
        grad_vc = g_pos * vo + (g_neg * vn).sum(axis=1)
        grad_vo = g_pos * vc
        grad_vn = g_neg * vc[:, None, :]

        # summed per-index updates with a per-row step cap: a token
        # repeated ~b/v times per batch on a tiny vocab summed into a
        # k-times-larger step and diverged to NaN (caught by the
        # contract-harness seed sweep); clipping the row update's L2 norm
        # leaves normal-regime dynamics untouched and bounds every step
        def scatter_clipped(tbl, ids, grads):
            upd = jnp.zeros_like(tbl).at[ids].add(grads)
            norm = jnp.linalg.norm(upd, axis=1, keepdims=True)
            upd = upd * jnp.minimum(1.0, clip_norm / jnp.maximum(norm, 1e-12))
            return tbl - lr * upd

        in_emb = scatter_clipped(in_emb, c, grad_vc)
        out_emb = scatter_clipped(out_emb, ctx, grad_vo)
        out_emb = scatter_clipped(
            out_emb, neg.reshape(-1),
            grad_vn.reshape(-1, grad_vn.shape[-1]),
        )
        return (in_emb, out_emb), None

    emb, _ = jax.lax.scan(step, vocab_emb, jnp.arange(steps) % centers.shape[0])
    return emb


class OpWord2VecModel(Transformer):
    input_types = [TextList]
    output_type = OPVector

    def __init__(self, vocab: dict, vectors: np.ndarray, **kw) -> None:
        super().__init__(**kw)
        self.vocab = dict(vocab)
        self.vectors = np.asarray(vectors)

    def transform_columns(self, cols: Sequence[Column], ds: Dataset) -> Column:
        (col,) = cols
        assert isinstance(col, ListColumn)
        d = self.vectors.shape[1]
        out = np.zeros((len(col), d), dtype=np.float32)
        for i, toks in enumerate(col.values):
            idxs = [self.vocab[t] for t in toks if t in self.vocab]
            if idxs:
                out[i] = self.vectors[idxs].mean(axis=0)
        feat = self.input_features[0]
        meta = VectorMetadata(
            self.output_name,
            tuple(
                VectorColumnMeta(feat.name, feat.ftype.type_name(),
                                 descriptor_value=f"w2v_{j}")
                for j in range(d)
            ),
        ).reindexed()
        return VectorColumn(out, meta)

    def similar_words(self, word: str, top_k: int = 5) -> list[tuple[str, float]]:
        if word not in self.vocab:
            return []
        v = self.vectors[self.vocab[word]]
        norms = np.linalg.norm(self.vectors, axis=1) * (np.linalg.norm(v) + 1e-12)
        sims = self.vectors @ v / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        inv = {i: w for w, i in self.vocab.items()}
        return [
            (inv[i], float(sims[i])) for i in order if inv[i] != word
        ][:top_k]


class OpWord2Vec(Estimator):
    """Skip-gram negative-sampling embeddings (reference: OpWord2Vec.scala;
    vectorSize default 100, minCount 5, windowSize 5)."""

    input_types = [TextList]
    output_type = OPVector

    def __init__(self, vector_size: int = 100, min_count: int = 5,
                 window_size: int = 5, num_negatives: int = 5,
                 steps: int = 2000, batch: int = 256, seed: int = 42,
                 **kw) -> None:
        super().__init__(**kw)
        self.vector_size = vector_size
        self.min_count = min_count
        self.window_size = window_size
        self.num_negatives = num_negatives
        self.steps = steps
        self.batch = batch
        self.seed = seed

    def fit_model(self, cols: Sequence[Column], ds: Dataset):
        (col,) = cols
        assert isinstance(col, ListColumn)
        from collections import Counter

        counts: Counter = Counter()
        for toks in col.values:
            counts.update(toks)
        vocab = {
            w: i
            for i, (w, c) in enumerate(
                sorted(counts.items(), key=lambda wc: (-wc[1], wc[0]))
            )
            if c >= self.min_count
        }
        if not vocab:
            return OpWord2VecModel({}, np.zeros((0, self.vector_size)))
        rng = np.random.RandomState(self.seed)
        pairs = []
        for toks in col.values:
            idxs = [vocab[t] for t in toks if t in vocab]
            for i, c in enumerate(idxs):
                lo = max(0, i - self.window_size)
                hi = min(len(idxs), i + self.window_size + 1)
                for j in range(lo, hi):
                    if j != i:
                        pairs.append((c, idxs[j]))
        if not pairs:
            return OpWord2VecModel(vocab, np.zeros((len(vocab), self.vector_size)))
        pairs_arr = np.array(pairs, dtype=np.int32)
        n_batches = max(1, len(pairs_arr) // self.batch)
        take = n_batches * self.batch
        order = rng.permutation(len(pairs_arr))[:take]
        centers = pairs_arr[order, 0].reshape(n_batches, self.batch)
        contexts = pairs_arr[order, 1].reshape(n_batches, self.batch)
        negatives = rng.randint(
            0, len(vocab), size=(n_batches, self.batch, self.num_negatives)
        ).astype(np.int32)
        v = len(vocab)
        init = (
            jnp.asarray(rng.randn(v, self.vector_size).astype(np.float32) * 0.1),
            jnp.asarray(np.zeros((v, self.vector_size), dtype=np.float32)),
        )
        # clip scale: a legitimate aggregate row update grows ~sqrt(batch)
        # in the summed scatter and ~sqrt(dim) in per-component count; 1.0
        # is calibrated for the (256, 100) defaults, so scale from there
        clip = max(
            1.0,
            float(np.sqrt((self.batch / 256.0) * (self.vector_size / 100.0))),
        )
        in_emb, _ = _w2v_sgns_kernel(
            jnp.asarray(centers), jnp.asarray(contexts), jnp.asarray(negatives),
            init, steps=min(self.steps, n_batches * 50), clip_norm=clip,
        )
        return OpWord2VecModel(vocab, np.asarray(in_emb))
