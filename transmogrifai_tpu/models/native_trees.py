"""Python bridge to the native C++ histogram tree learner.

The host-side libxgboost-equivalent (SURVEY §2.9: the reference's one
native-backed estimator is xgboost4j -> JNI -> C++ libxgboost,
reference: core/build.gradle:27).  Emits the SAME flat-heap layout as the
jitted JAX kernels in tree_kernel.py, so prediction, serialization and
LOCO paths are backend-agnostic.  Returns None when the shared library is
unavailable (callers fall back to the JAX path).
"""
from __future__ import annotations

import weakref
from typing import Optional

import numpy as np

from ..utils import native


def available() -> bool:
    return native.has_tree_symbols()


def fit_forest(
    bins: np.ndarray,       # [n, d] int32
    stats_row: np.ndarray,  # [n, C] float32
    w_row: np.ndarray,      # [n] float32
    boot_w: np.ndarray,     # [T, n] float32
    feat_masks: np.ndarray, # [T, d] bool
    seeds: np.ndarray,      # [T] uint64
    max_depth: int,
    max_bins: int,
    impurity_kind: str,
    min_instances_per_node: float = 1.0,
    min_info_gain: float = 0.0,
    feature_subset_p: float = 1.0,
    n_threads: int = 0,
) -> Optional[tuple]:
    lib = native.get_lib()
    if lib is None or not hasattr(lib, "tx_fit_forest_hist"):
        return None
    bins = np.ascontiguousarray(bins, dtype=np.int32)
    stats_row = np.ascontiguousarray(stats_row, dtype=np.float32)
    w_row = np.ascontiguousarray(w_row, dtype=np.float32)
    boot_w = np.ascontiguousarray(boot_w, dtype=np.float32)
    masks = np.ascontiguousarray(feat_masks, dtype=np.uint8)
    seeds = np.ascontiguousarray(seeds, dtype=np.uint64)
    n, d = bins.shape
    T = boot_w.shape[0]
    C = stats_row.shape[1]
    M = 2 ** (max_depth + 1) - 1
    hf = np.zeros((T, M), dtype=np.int32)
    ht = np.zeros((T, M), dtype=np.int32)
    hl = np.zeros((T, M), dtype=np.uint8)
    hv = np.zeros((T, M, C), dtype=np.float32)
    lib.tx_fit_forest_hist(
        bins.ctypes.data, stats_row.ctypes.data, w_row.ctypes.data,
        boot_w.ctypes.data, masks.ctypes.data, seeds.ctypes.data,
        np.int64(n), np.int32(d), np.int32(T),
        np.int32(max_depth), np.int32(max_bins), np.int32(C),
        np.int32(1 if impurity_kind == "variance" else 0),
        float(min_instances_per_node), float(min_info_gain),
        float(feature_subset_p), np.int32(n_threads),
        hf.ctypes.data, ht.ctypes.data, hl.ctypes.data, hv.ctypes.data,
    )
    return hf, ht, hl.astype(bool), hv


def fit_gbt(
    bins: np.ndarray,   # [n, d] int32
    y: np.ndarray,      # [n] float32
    w_row: np.ndarray,  # [n] float32
    num_trees: int,
    max_depth: int,
    max_bins: int,
    is_classification: bool,
    step_size: float,
    f0: float,
    min_instances_per_node: float = 1.0,
    min_info_gain: float = 0.0,
) -> Optional[tuple]:
    lib = native.get_lib()
    if lib is None or not hasattr(lib, "tx_fit_gbt_hist"):
        return None
    bins = np.ascontiguousarray(bins, dtype=np.int32)
    y = np.ascontiguousarray(y, dtype=np.float32)
    w_row = np.ascontiguousarray(w_row, dtype=np.float32)
    n, d = bins.shape
    C = 4
    M = 2 ** (max_depth + 1) - 1
    hf = np.zeros((num_trees, M), dtype=np.int32)
    ht = np.zeros((num_trees, M), dtype=np.int32)
    hl = np.zeros((num_trees, M), dtype=np.uint8)
    hv = np.zeros((num_trees, M, C), dtype=np.float32)
    F = np.zeros((n,), dtype=np.float32)
    lib.tx_fit_gbt_hist(
        bins.ctypes.data, y.ctypes.data, w_row.ctypes.data,
        np.int64(n), np.int32(d), np.int32(num_trees),
        np.int32(max_depth), np.int32(max_bins),
        np.int32(1 if is_classification else 0),
        float(step_size), float(f0),
        float(min_instances_per_node), float(min_info_gain),
        hf.ctypes.data, ht.ctypes.data, hl.ctypes.data, hv.ctypes.data,
        F.ctypes.data,
    )
    return hf, ht, hl.astype(bool), hv


#: fitted-heap arrays -> lib-ready contiguous arrays.  Per-batch
#: serving calls predict with the SAME fitted heaps every time; the
#: bool->uint8 astype alone copied ~T*M bytes per call.  Keyed by the
#: id of the bool leaf-mask (the one member the prepared copies never
#: alias), verified by weakrefs to all four members so a recycled id
#: can never serve another forest's arrays; a finalizer on the mask
#: evicts the entry when the fitted model is collected, so superseded
#: generations under hot-swap serving don't stay pinned.  Bounded as
#: belt-and-braces like the other serving memos.
_PREPARED_HEAPS: dict = {}
#: id(leaf-mask) -> weakref.finalize, kept across cache eviction so a
#: long-lived forest churning through a full cache registers exactly
#: ONE finalizer, not one per re-insertion
_HEAP_FINALIZERS: dict = {}
_MAX_PREPARED_HEAPS = 32


def _evict_prepared(key: int) -> None:
    _PREPARED_HEAPS.pop(key, None)
    _HEAP_FINALIZERS.pop(key, None)


def _prepared(heaps: tuple) -> tuple:
    hf, ht, hl, hv = heaps
    key = id(hl)
    hit = _PREPARED_HEAPS.get(key)
    if hit is not None and all(
        r() is a for r, a in zip(hit[0], heaps)
    ):
        return hit[1]
    prep = (
        np.ascontiguousarray(hf, dtype=np.int32),
        np.ascontiguousarray(ht, dtype=np.int32),
        np.ascontiguousarray(hl, dtype=np.uint8),
        np.ascontiguousarray(hv, dtype=np.float32),
    )
    try:
        refs = tuple(weakref.ref(a) for a in heaps)
    except TypeError:
        # non-ndarray heap members (python-fallback fits): no memo,
        # the per-call copies are the price of the fallback path
        return prep
    if len(_PREPARED_HEAPS) >= _MAX_PREPARED_HEAPS:
        # one-out-one-in (FIFO), not clear(): a full cache under
        # round-robin traffic must not throw away every OTHER model's
        # prepared arrays on each insert
        _PREPARED_HEAPS.pop(next(iter(_PREPARED_HEAPS)))
    _PREPARED_HEAPS[key] = (refs, prep)
    fin = _HEAP_FINALIZERS.get(key)
    if fin is None or fin.peek() is None or fin.peek()[0] is not hl:
        _HEAP_FINALIZERS[key] = weakref.finalize(
            hl, _evict_prepared, key
        )
    return prep


def predict_forest(
    bins: np.ndarray, heaps: tuple, max_depth: int
) -> Optional[np.ndarray]:
    """Mean normalized per-tree outputs [n, C-1] (same contract as
    tree_kernel.predict_forest), computed host-side."""
    lib = native.get_lib()
    if lib is None or not hasattr(lib, "tx_predict_forest_hist"):
        return None
    bins = np.ascontiguousarray(bins, dtype=np.int32)
    hf, ht, hl8, hv = _prepared(heaps)
    n, d = bins.shape
    T, M, C = hv.shape
    out = np.zeros((n, C - 1), dtype=np.float32)
    lib.tx_predict_forest_hist(
        bins.ctypes.data, hf.ctypes.data, ht.ctypes.data, hl8.ctypes.data,
        hv.ctypes.data, np.int64(n), np.int32(d), np.int32(T),
        np.int32(max_depth), np.int32(C), out.ctypes.data,
    )
    return out


def bin_data(X: np.ndarray, edges: np.ndarray) -> Optional[np.ndarray]:
    lib = native.get_lib()
    if lib is None or not hasattr(lib, "tx_bin_data"):
        return None
    X = np.ascontiguousarray(X, dtype=np.float32)
    edges = np.ascontiguousarray(edges, dtype=np.float32)
    n, d = X.shape
    out = np.empty((n, d), dtype=np.int32)
    lib.tx_bin_data(
        X.ctypes.data, edges.ctypes.data, np.int64(n), np.int32(d),
        np.int32(edges.shape[1]), out.ctypes.data,
    )
    return out
