"""Tree-ensemble estimators: random forest, single tree, gradient boosting.

Counterparts of OpRandomForestClassifier / OpRandomForestRegressor /
OpDecisionTreeClassifier / OpDecisionTreeRegressor / OpGBTClassifier /
OpGBTRegressor / (OpXGBoost* hist-mode equivalent) (reference: core/.../
impl/classification/*.scala, impl/regression/*.scala, xgboost4j dep
core/build.gradle:27).  All training runs through the jitted histogram
kernels in tree_kernel.py; defaults mirror the reference grids
(maxDepth 5->grid {3,6,12}, numTrees 50, maxBins 32, impurity gini/variance,
featureSubsetStrategy auto = sqrt(d) classification / d/3 regression).
"""
from __future__ import annotations

from typing import Any, Optional

import os

import jax
import jax.numpy as jnp
import numpy as np

from . import native_trees
from .base import PredictorEstimator
from .tree_kernel import (
    bin_data,
    effective_max_depth,
    fit_forest,
    fit_forest_folds,
    fit_forest_folds_grid,
    fit_gbt_folds,
    fit_gbt_folds_grid,
    heap_impurity_importances,
    predict_forest,
    predict_forest_np,
    predict_forest_stats_np,
    predict_tree,
    quantile_bin_edges,
)


_NATIVE_ROWS_CUTOFF = 50_000  # below this, host C++ beats device round-trips


def _resolve_backend(requested: str, n_rows: int | None = None) -> str:
    """'jax' | 'native' | 'auto'.  auto = the C++ host learner when no
    accelerator is attached (local/CPU runs - the Spark-local analog) OR
    when the dataset is small enough (< TX_TREE_NATIVE_ROWS, default 50k)
    that per-dispatch latency + compile dominates any device win - a
    712-row Titanic grid takes ~16 s through the C++ learner vs minutes
    of chunked device dispatches; the device histogram kernels take over
    at the row counts where the one-segment-sum scatter actually pays.
    TX_TREE_BACKEND overrides."""
    requested = os.environ.get("TX_TREE_BACKEND", requested)
    if requested == "native":
        return "native" if native_trees.available() else "jax"
    if requested == "auto":
        try:
            on_cpu = jax.default_backend() == "cpu"
        except Exception:
            on_cpu = True
        cutoff = int(os.environ.get("TX_TREE_NATIVE_ROWS",
                                    _NATIVE_ROWS_CUTOFF))
        small = n_rows is not None and n_rows < cutoff
        return (
            "native" if ((on_cpu or small) and native_trees.available())
            else "jax"
        )
    return "jax"


_EDGE_SAMPLE_CAP = 1 << 19  # rows used for the quantile sketch on huge inputs


def _sampled_bin_edges(X, max_bins: int, seed: int) -> np.ndarray:
    """Quantile edges from a row subsample above the cap (the xgboost-hist /
    Spark findSplits approx-sketch move; exact quantiles below the cap)."""
    n = X.shape[0]
    if n <= _EDGE_SAMPLE_CAP:
        return quantile_bin_edges(X, max_bins)
    # with-replacement draw: O(cap) and statistically equivalent for a
    # quantile sketch (choice(replace=False) would build an O(n)
    # permutation).  X[idx] gathers BEFORE np.asarray so a device-resident
    # X ships only the sample, not the full matrix.
    idx = np.random.RandomState(seed).randint(0, n, _EDGE_SAMPLE_CAP)
    return quantile_bin_edges(np.asarray(X[idx]), max_bins)


def _bin_for_backend(X, edges):
    """Bin assignment routed to the fastest path: the pallas device kernel
    when a TPU is attached (parallel/pallas_kernels.bin_matrix - stays in
    HBM), host C++/searchsorted otherwise."""
    try:
        if jax.default_backend() == "tpu":
            from ..parallel.pallas_kernels import bin_matrix

            return bin_matrix(X, edges)  # no host round-trip: the kernel
    except Exception:                    # jnp.asarray's a device X itself
        pass
    return bin_data(np.asarray(X), edges)


def _bins_cast(bins, max_bins: int):
    """Cast the binned matrix to its device dtype (int8 when every bin id
    fits, tree_kernel.bins_device_dtype): the [n, d] bins read recurs in
    EVERY level scan of every tree, so int8 carries the dominant
    per-level HBM term at 1/4 the traffic.  Host numpy and device jnp
    arrays both cast in place; the native C++ bridge re-coerces to int32
    on entry, so the cast is backend-neutral."""
    from .tree_kernel import bins_device_dtype

    dt = bins_device_dtype(max_bins)
    if dt == jnp.int8:
        return (
            bins.astype(jnp.int8)
            if isinstance(bins, jax.Array)
            else np.asarray(bins).astype(np.int8)
        )
    return bins


def _pad_axis_to_multiple(arr, multiple: int, axis: int):
    """Zero-pad ``axis`` to the shard multiple.  Device-resident arrays
    pad with jnp (stays in HBM); host arrays with numpy."""
    rem = (-arr.shape[axis]) % multiple
    if rem == 0:
        return arr
    pad_shape = list(arr.shape)
    pad_shape[axis] = rem
    if isinstance(arr, jax.Array):
        return jnp.concatenate(
            [arr, jnp.zeros(tuple(pad_shape), arr.dtype)], axis=axis
        )
    arr = np.asarray(arr)
    return np.concatenate(
        [arr, np.zeros(tuple(pad_shape), arr.dtype)], axis=axis
    )


def _tree_cv_mesh():
    """The product 'data' mesh for tree fold fits, or None.  Same
    multi-host contract as fused_moments_sharded: on a multi-process
    runtime, callers must pass device-resident global jax.Arrays (the
    per-array guard lives in _place)."""
    from ..parallel.mesh import data_mesh_or_none

    return data_mesh_or_none()


def _place(arr, mesh, row_axis: int):
    """Pad ``row_axis`` to the shard multiple and place the array with
    that axis sharded over 'data' (device-resident arrays reshard
    device-to-device; host arrays upload directly into their shards)."""
    if jax.process_count() > 1 and not isinstance(arr, jax.Array):
        raise ValueError(
            "tree fold fits received a host-resident array on a "
            "multi-process runtime; assemble global jax.Arrays with "
            "jax.make_array_from_process_local_data before fitting "
            "(host inputs are only valid when replicated on every process)"
        )
    from jax.sharding import NamedSharding, PartitionSpec as P

    arr = _pad_axis_to_multiple(arr, mesh.shape["data"], row_axis)
    spec = [None] * np.ndim(arr)
    spec[row_axis] = "data"
    if not isinstance(arr, jax.Array):
        arr = np.ascontiguousarray(arr)
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))


def _shard_fold_inputs(bins, stats_or_y, W, boot=None):
    """Row-shard the fold-fit inputs over the product 'data' mesh when more
    than one device is attached (the Spark-partition analog for the tree
    CV fan-out; LR's batched path already does this in the validator).
    Rows pad to the shard multiple; padded rows carry ZERO fold weight, so
    they touch no histogram statistic (stats are weighted by W inside
    fit_tree).  Without a mesh the inputs pass through jnp.asarray
    untouched; with one, device-resident arrays (e.g. a pallas-binned
    matrix) pad and reshard WITHOUT a host round-trip.

    stats_or_y: [n, C] per-row stat channels (forest) or [n] labels (GBT).
    """
    mesh = _tree_cv_mesh()
    if mesh is None:
        return (
            jnp.asarray(bins), jnp.asarray(stats_or_y), jnp.asarray(W),
            None if boot is None else jnp.asarray(boot),
        )
    return (
        _place(bins, mesh, 0),
        _place(stats_or_y, mesh, 0),
        _place(W, mesh, 1),
        None if boot is None else _place(boot, mesh, 1),
    )


def _subset_fraction(strategy: str, d: int, is_classification: bool) -> float:
    if strategy == "all":
        return 1.0
    if strategy == "sqrt" or (strategy == "auto" and is_classification):
        return min(1.0, float(np.sqrt(d)) / d)
    if strategy == "onethird" or (strategy == "auto" and not is_classification):
        return 1.0 / 3.0
    return 1.0


def _bin_xla(X, edges):
    """jax-traceable mirror of ``bin_data``: per-feature searchsorted
    (side='left') over the fitted quantile edges.  NaN values map to
    +inf first - numpy's searchsorted ranks NaN after every finite edge
    (bin = n_edges) while XLA's comparison-based binary search would
    rank it 0; +inf lands both on the same tail bin."""
    safe = jnp.where(jnp.isnan(X), jnp.inf, X)

    def one(e, x):
        return jnp.searchsorted(e, x, side="left")

    return jax.vmap(one, in_axes=(0, 1), out_axes=1)(
        jnp.asarray(edges), safe
    ).astype(jnp.int32)


#: packed-node field widths (_forest_stats_xla): feature 20 bits,
#: threshold-bin 11 bits, leaf flag in the int32 sign bit
_PACK_F_BITS = 20
_PACK_T_BITS = 11


def _forest_stats_xla(bins, heaps, max_depth: int):
    """jax-traceable mirror of tree_kernel.predict_forest_stats_np: walk
    EVERY tree's flat heap as one [T, n] gather frontier -> [T, n, C]
    raw leaf stats.  max_depth gather steps, identical index arithmetic,
    so the gathered leaf stats are bit-equal to the numpy walk.

    The per-level (feature, threshold, leaf) triple is bit-packed into
    ONE int32 per node at trace time (heaps are concrete host arrays
    here, so the packing constant-folds): one flat 1-D gather per level
    instead of three 2-D ones - measured ~3.5x faster than the naive
    advanced-indexing walk on the 50-tree depth-12 RF winner.  Heaps too
    wide for the packing (>= 2^20 features or >= 2^11 threshold bins)
    take the unpacked walk, bit-identical either way."""
    hf, ht, hl, hv = (np.asarray(h) for h in heaps)
    if hf.ndim == 1:  # single tree -> add tree axis (numpy-walk parity)
        hf, ht, hl, hv = hf[None], ht[None], hl[None], hv[None]
    T, M = hf.shape
    n = bins.shape[0]
    idx = jnp.zeros((T, n), dtype=jnp.int32)
    base = (jnp.arange(T, dtype=jnp.int32) * M)[:, None]
    if hf.max(initial=0) < (1 << _PACK_F_BITS) and \
            ht.max(initial=0) < (1 << _PACK_T_BITS):
        d = bins.shape[1]
        packed = jnp.asarray(
            (hf.astype(np.int64)
             | (ht.astype(np.int64) << _PACK_F_BITS)
             | (hl.astype(np.int64) << 31))
            .astype(np.uint32).view(np.int32).ravel()
        )
        binsf = bins.ravel()
        rowoff = (jnp.arange(n, dtype=jnp.int32) * d)[None, :]
        f_mask = (1 << _PACK_F_BITS) - 1
        for _ in range(max_depth):
            p = packed[base + idx]
            f = p & f_mask
            thr = (p & 0x7FFFFFFF) >> _PACK_F_BITS
            row_bin = binsf[rowoff + f]
            nxt = idx * 2 + 1 + (row_bin > thr).astype(jnp.int32)
            idx = jnp.where(p < 0, idx, nxt)  # sign bit = leaf
        return jnp.asarray(hv.reshape(T * M, -1))[base + idx]
    hff, htf, hlf = (jnp.asarray(a.ravel()) for a in (hf, ht, hl))
    rows = jnp.arange(n)[None, :]
    for _ in range(max_depth):
        g = base + idx
        f = hff[g]
        thr = htf[g]
        leaf = hlf[g]
        row_bin = bins[rows, f]
        nxt = idx * 2 + 1 + (row_bin > thr).astype(jnp.int32)
        idx = jnp.where(leaf, idx, nxt)
    return jnp.asarray(hv.reshape(T * M, -1))[base + idx]


def _seq_sum0(x):
    """Sequential tree-order sum over axis 0, unrolled.  numpy's axis-0
    ``add.reduce`` adds the T slices strictly in order (its pairwise
    summation applies only to contiguous innermost-axis reductions), and
    XLA does not reassociate explicit separate adds - so the float
    accumulation is bit-equal to the numpy predict path's ``.sum(axis=0)``
    / ``.mean(axis=0)``."""
    acc = x[0]
    for t in range(1, x.shape[0]):
        acc = acc + x[t]
    return acc


class _TreeEnsembleBase(PredictorEstimator):
    is_classification = True
    # fused serving (local/fused.py): predict_arrays_np is ONE flat-heap
    # native/numpy batch call over host params - pure and closable; the
    # f32 binning front end makes a float32 feed bit-identical
    lowerable = True
    predict_f32_exact = True

    def __init__(
        self,
        num_trees: int = 50,
        max_depth: int = 5,
        max_bins: int = 32,
        min_instances_per_node: int = 1,
        min_info_gain: float = 0.0,
        subsampling_rate: float = 1.0,
        feature_subset_strategy: str = "auto",
        seed: int = 42,
        backend: str = "auto",
        depth_cap: str = "auto",
        **kw,
    ) -> None:
        super().__init__(**kw)
        p = self.params
        p.setdefault("backend", backend)
        p.setdefault("num_trees", num_trees)
        p.setdefault("max_depth", max_depth)
        p.setdefault("max_bins", max_bins)
        p.setdefault("min_instances_per_node", min_instances_per_node)
        p.setdefault("min_info_gain", min_info_gain)
        p.setdefault("subsampling_rate", subsampling_rate)
        p.setdefault("feature_subset_strategy", feature_subset_strategy)
        p.setdefault("seed", seed)
        p.setdefault("depth_cap", depth_cap)  # "auto" | "off" (honor as-is)

    # -- shared helpers -----------------------------------------------------
    def _stats_rows(self, y: np.ndarray) -> tuple[np.ndarray, int, str, np.ndarray]:
        """Build per-row stat channels. Returns (stats [n, C], C, impurity,
        classes)."""
        if self.is_classification:
            classes = np.unique(y)
            onehot = (y[:, None] == classes[None, :]).astype(np.float32)
            stats = np.concatenate(
                [np.ones((len(y), 1), dtype=np.float32), onehot], axis=1
            )
            return stats, stats.shape[1], "gini", classes
        stats = np.stack(
            [np.ones_like(y), y, y * y], axis=1
        ).astype(np.float32)
        return stats, 3, "variance", np.array([])


class _RandomForest(_TreeEnsembleBase):
    single_tree = False

    def _forest_inputs(self, X, y):
        n, d = X.shape
        p = self.params
        edges = _sampled_bin_edges(X, int(p["max_bins"]), int(p["seed"]))
        bins = _bins_cast(_bin_for_backend(X, edges), int(p["max_bins"]))
        stats, C, imp, classes = self._stats_rows(y)
        T = 1 if self.single_tree else int(p["num_trees"])
        rng = np.random.RandomState(p["seed"])
        if self.single_tree:
            boot = np.ones((1, n), dtype=np.float32)
            subset_p = 1.0
        else:
            boot = rng.poisson(
                p["subsampling_rate"], size=(T, n)
            ).astype(np.float32)
            subset_p = _subset_fraction(
                p["feature_subset_strategy"], d, self.is_classification
            )
        feat_masks = np.ones((T, d), dtype=bool)
        seed_ints = rng.randint(0, 2**31 - 1, size=T)
        depth = effective_max_depth(
            int(p["max_depth"]), n, float(p["min_instances_per_node"]),
            d, int(p["max_bins"]), C, cap=str(p.get("depth_cap", "auto")),
        )
        return (edges, bins, stats, C, imp, classes, boot, feat_masks,
                seed_ints, subset_p, depth)

    def fit_arrays(self, X, y, w=None) -> Any:
        n, d = X.shape
        p = self.params
        w = np.ones(n, dtype=np.float32) if w is None else np.asarray(w, np.float32)
        (edges, bins, stats, C, imp, classes, boot, feat_masks,
         seed_ints, subset_p, depth) = self._forest_inputs(X, y)
        backend = _resolve_backend(str(p.get("backend", "auto")), n)
        if backend == "native":
            heaps = native_trees.fit_forest(
                bins, stats, w, boot, feat_masks,
                seed_ints.astype(np.uint64),
                max_depth=depth, max_bins=int(p["max_bins"]),
                impurity_kind=imp,
                min_instances_per_node=float(p["min_instances_per_node"]),
                min_info_gain=float(p["min_info_gain"]),
                feature_subset_p=float(subset_p),
            )
        else:
            heaps = None
        if heaps is None:
            keys = jax.vmap(jax.random.PRNGKey)(jnp.asarray(seed_ints))
            heaps = fit_forest(
                jnp.asarray(bins), jnp.asarray(stats), jnp.asarray(w),
                jnp.asarray(boot), jnp.asarray(feat_masks), keys,
                max_depth=depth, max_bins=int(p["max_bins"]),
                impurity_kind=imp, n_stats=C,
                min_instances_per_node=float(p["min_instances_per_node"]),
                min_info_gain=float(p["min_info_gain"]),
                feature_subset_p=float(subset_p),
            )
        return {
            "edges": edges,
            "heaps": tuple(np.asarray(h) for h in heaps),
            "classes": classes,
            "max_depth": depth,
        }

    def fit_arrays_folds(self, X, y, W) -> list:
        """One vmapped fit over [F, n] fold-weight masks: shared binning,
        shared bootstrap - the forest CV fan-out."""
        p = self.params
        (edges, bins, stats, C, imp, classes, boot, feat_masks,
         seed_ints, subset_p, depth) = self._forest_inputs(X, y)
        backend = _resolve_backend(str(p.get("backend", "auto")), X.shape[0])
        if backend == "native":
            W = np.asarray(W, np.float32)
            out = []
            for f in range(len(W)):
                heaps_f = native_trees.fit_forest(
                    bins, stats, W[f], boot, feat_masks,
                    seed_ints.astype(np.uint64),
                    max_depth=depth, max_bins=int(p["max_bins"]),
                    impurity_kind=imp,
                    min_instances_per_node=float(p["min_instances_per_node"]),
                    min_info_gain=float(p["min_info_gain"]),
                    feature_subset_p=float(subset_p),
                )
                if heaps_f is None:
                    break
                out.append({
                    "edges": edges,
                    "heaps": heaps_f,
                    "classes": classes,
                    "max_depth": depth,
                })
            if len(out) == len(W):
                return out
        keys = jax.vmap(jax.random.PRNGKey)(jnp.asarray(seed_ints))
        bins_d, stats_d, W_d, boot_d = _shard_fold_inputs(
            bins, stats, np.asarray(W, np.float32), boot
        )
        heaps = fit_forest_folds(
            bins_d, stats_d, W_d, boot_d, jnp.asarray(feat_masks), keys,
            max_depth=depth, max_bins=int(p["max_bins"]),
            impurity_kind=imp, n_stats=C,
            min_instances_per_node=float(p["min_instances_per_node"]),
            min_info_gain=float(p["min_info_gain"]),
            feature_subset_p=float(subset_p),
        )
        heaps = tuple(np.asarray(h) for h in heaps)
        return [
            {
                "edges": edges,
                "heaps": tuple(h[f] for h in heaps),
                "classes": classes,
                "max_depth": depth,
            }
            for f in range(len(W))
        ]

    def fit_arrays_folds_grid(self, X, y, W, grid) -> Optional[list]:
        """Whole-grid CV fan-out: groups grid points by their STATIC shape
        params (effective depth, bins, trees, subset strategy, seed), then
        fits each group's configs x folds as ONE device dispatch
        (tree_kernel.fit_forest_folds_grid; min_info_gain and
        min_instances ride a traced lax.map axis).  Returns params[g][f]
        aligned with ``grid``, or None when the native host backend is
        active (its per-config C++ loop is already the fast path there).
        """
        p0 = self.params
        if _resolve_backend(str(p0.get("backend", "auto")),
                            X.shape[0]) == "native":
            return None
        n, d = X.shape
        cands = [self.with_params(**pmap) for pmap in grid]
        n_stats = (len(np.unique(y)) + 1) if self.is_classification else 3
        groups: dict[tuple, list[int]] = {}
        for j, cand in enumerate(cands):
            p = cand.params
            depth = effective_max_depth(
                int(p["max_depth"]), n, float(p["min_instances_per_node"]),
                d, int(p["max_bins"]), n_stats,
                cap=str(p.get("depth_cap", "auto")),
            )
            key = (
                depth, int(p["max_bins"]), int(p["num_trees"]),
                str(p["feature_subset_strategy"]), int(p["seed"]),
                float(p["subsampling_rate"]),
            )
            groups.setdefault(key, []).append(j)
        results: list = [None] * len(grid)
        W32 = np.asarray(W, np.float32)
        for key, js in groups.items():
            rep = cands[js[0]]
            (edges, bins, stats, C, imp, classes, boot, feat_masks,
             seed_ints, subset_p, depth) = rep._forest_inputs(X, y)
            assert depth == key[0]
            minipn_g = jnp.asarray(
                [float(cands[j].params["min_instances_per_node"]) for j in js],
                jnp.float32,
            )
            minig_g = jnp.asarray(
                [float(cands[j].params["min_info_gain"]) for j in js],
                jnp.float32,
            )
            keys = jax.vmap(jax.random.PRNGKey)(jnp.asarray(seed_ints))
            bins_d, stats_d, W_d, boot_d = _shard_fold_inputs(
                bins, stats, W32, boot
            )
            heaps = fit_forest_folds_grid(
                bins_d, stats_d, W_d, boot_d, jnp.asarray(feat_masks), keys,
                minipn_g, minig_g,
                max_depth=depth, max_bins=int(rep.params["max_bins"]),
                impurity_kind=imp, n_stats=C,
                feature_subset_p=float(subset_p),
            )
            heaps = tuple(np.asarray(h) for h in heaps)  # [G', F, T, ...]
            for gi, j in enumerate(js):
                results[j] = [
                    {
                        "edges": edges,
                        "heaps": tuple(h[gi][f] for h in heaps),
                        "classes": classes,
                        "max_depth": depth,
                    }
                    for f in range(len(W))
                ]
        return results

    def fused_tree_plan(self, X, y, W, grid):
        """Fused-training seam (local/fused_train.py, ISSUE 15): host
        prep (binning, bootstrap, rng keys - identical to
        fit_arrays_folds_grid's) plus traceable fit/predict closures the
        one-program jit composes with the shared metric stage.  Raises
        ``ValueError`` naming the reason when this grid cannot ride one
        fused dispatch (native backend, multiple static-shape groups,
        watchdog-chunked dispatch) - the validator falls back to the
        existing path and records the reason."""
        from .tree_kernel import (
            fit_forest_folds_grid_core,
            fits_per_dispatch,
        )

        n, d = X.shape
        if _resolve_backend(str(self.params.get("backend", "auto")),
                            n) == "native":
            raise ValueError("native_backend")
        cands = [self.with_params(**pmap) for pmap in grid]
        n_stats = (len(np.unique(y)) + 1) if self.is_classification else 3
        keys_seen = set()
        for cand in cands:
            p = cand.params
            depth = effective_max_depth(
                int(p["max_depth"]), n, float(p["min_instances_per_node"]),
                d, int(p["max_bins"]), n_stats,
                cap=str(p.get("depth_cap", "auto")),
            )
            keys_seen.add((
                depth, int(p["max_bins"]), int(p["num_trees"]),
                str(p["feature_subset_strategy"]), int(p["seed"]),
                float(p["subsampling_rate"]),
            ))
        if len(keys_seen) > 1:
            raise ValueError("grid_shape_groups")
        (edges, bins, stats, C, imp, classes, boot, feat_masks,
         seed_ints, subset_p, depth) = cands[0]._forest_inputs(X, y)
        G, F, T = len(grid), len(W), boot.shape[0]
        cap = fits_per_dispatch(depth, n, d, int(cands[0].params["max_bins"]),
                                C)
        if G * F * T > cap:
            raise ValueError("dispatch_chunked")
        if self.is_classification and len(classes) < 2:
            raise ValueError("single_class")
        max_bins = int(cands[0].params["max_bins"])
        keys = jax.vmap(jax.random.PRNGKey)(jnp.asarray(seed_ints))
        arrays = {
            "bins": np.asarray(bins),
            "stats": stats,
            "w_rows": np.asarray(W, np.float32),
            "boot": boot,
            "feat_masks": feat_masks,
            "keys": np.asarray(keys),
            "minipn_g": np.asarray(
                [float(c.params["min_instances_per_node"]) for c in cands],
                np.float32),
            "minig_g": np.asarray(
                [float(c.params["min_info_gain"]) for c in cands],
                np.float32),
        }
        is_classification = self.is_classification

        def fit(a):
            return fit_forest_folds_grid_core(
                a["bins"], a["stats"], a["w_rows"], a["boot"],
                a["feat_masks"], a["keys"], a["minipn_g"], a["minig_g"],
                max_depth=depth, max_bins=max_bins, impurity_kind=imp,
                n_stats=C, feature_subset_p=float(subset_p),
            )

        def score(state, bins_v, f, gi):
            # mirrors predict_arrays' jax route per (g, f): the SAME
            # predict_forest kernel over the (gi, f) heap slice - every
            # operand a device buffer, so the f32 scores are bit-equal
            # to the per-candidate dispatches
            out = predict_forest(
                bins_v, tuple(h[gi, f] for h in state), max_depth=depth)
            return out[:, 1] if is_classification else out[:, 0]

        return {
            "arrays": arrays,
            "donate": ("stats", "w_rows", "boot"),
            "bins_key": "bins",
            "fit": fit,
            "n_state": 4,
            "score": score,
            "sig": ("forest", depth, max_bins, G, F, T, C, imp,
                    float(subset_p), is_classification),
        }

    def predict_arrays(self, params: Any, X: np.ndarray):
        out = None
        if _resolve_backend(str(self.params.get("backend", "auto")),
                            X.shape[0]) == "native":
            bins = bin_data(np.asarray(X, np.float32), params["edges"])
            out = native_trees.predict_forest(
                bins, params["heaps"], params["max_depth"]
            )
        else:
            bins = _bin_for_backend(np.asarray(X, np.float32),
                                    params["edges"])
        if out is None:
            out = np.asarray(
                predict_forest(
                    jnp.asarray(bins),
                    tuple(jnp.asarray(h) for h in params["heaps"]),
                    max_depth=params["max_depth"],
                )
            )
        if self.is_classification:
            prob = out  # [n, K] mean class distributions
            classes = params["classes"]
            pred = classes[np.argmax(prob, axis=1)]
            return pred.astype(np.float64), prob, prob
        return out[:, 0].astype(np.float64), None, None

    def predict_arrays_np(self, params: Any, X: np.ndarray):
        # the engine-free serving path (LocalScorer prefer_numpy): ONE
        # flat-heap C++ call per batch when the native lib is present,
        # else the vectorized all-trees numpy walk.  This used to loop
        # T trees in python (T x max_depth tiny-array numpy dispatches
        # per call = ~6 ms/row on the 50-tree RF winner, VERDICT r5
        # Weak #4); both routes below are batch-first and micro-second
        # scale at batch-of-1.
        bins = bin_data(np.asarray(X, np.float32), params["edges"])
        out = native_trees.predict_forest(
            bins, params["heaps"], params["max_depth"]
        )
        if out is None:
            out = predict_forest_np(bins, params["heaps"],
                                    params["max_depth"])
        if self.is_classification:
            classes = params["classes"]
            pred = classes[np.argmax(out, axis=1)]
            return pred.astype(np.float64), out, out
        return out[:, 0].astype(np.float64), None, None

    def predict_arrays_xla(self, params: Any, X):
        """jax-traceable mirror of ``predict_arrays_np`` for the XLA
        fused backend (local/fused_xla.py): searchsorted binning +
        all-trees flat-heap gather traversal + the numpy path's exact
        f32 normalize/mean arithmetic order (bit-parity pinned by
        tests/test_fused_xla.py)."""
        stats = _forest_stats_xla(
            _bin_xla(X, params["edges"]), params["heaps"],
            params["max_depth"],
        )
        w = jnp.maximum(stats[..., 0:1], jnp.float32(1e-12))
        out = _seq_sum0(stats[..., 1:] / w) / stats.shape[0]
        if self.is_classification:
            classes = jnp.asarray(np.asarray(params["classes"],
                                             dtype=np.float64))
            pred = classes[jnp.argmax(out, axis=1)]
            return pred.astype(jnp.float64), out, out
        return out[:, 0].astype(jnp.float64), None, None

    def contributions(self, params: Any) -> Optional[np.ndarray]:
        """Impurity-decrease feature importances recovered from the stored
        heaps (Spark featureImportances contract - gain x node weight per
        split, per-tree normalized, averaged; reference:
        ModelInsights.scala:435-525)."""
        return heap_impurity_importances(
            params["heaps"],
            int(params["edges"].shape[0]),
            "gini" if self.is_classification else "variance",
        )


class OpRandomForestClassifier(_RandomForest):
    model_type = "OpRandomForestClassifier"
    is_classification = True


class OpRandomForestRegressor(_RandomForest):
    model_type = "OpRandomForestRegressor"
    is_classification = False


class OpDecisionTreeClassifier(_RandomForest):
    model_type = "OpDecisionTreeClassifier"
    is_classification = True
    single_tree = True


class OpDecisionTreeRegressor(_RandomForest):
    model_type = "OpDecisionTreeRegressor"
    is_classification = False
    single_tree = True


class _GBT(_TreeEnsembleBase):
    """Gradient boosting with regression trees on the loss gradient
    (reference: OpGBTClassifier/OpGBTRegressor; MLlib GradientBoostedTrees
    semantics - logistic loss for classification, squared for regression,
    stepSize default 0.1, numTrees default 20)."""

    def __init__(self, num_trees: int = 20, step_size: float = 0.1, **kw) -> None:
        super().__init__(num_trees=num_trees, **kw)
        self.params.setdefault("step_size", step_size)

    def _check_labels(self, y) -> None:
        """Logistic-loss boosting is binary (Spark: 'GBTClassifier
        currently only supports binary classification'); regressors take
        any y.  The shared base guard also rejects non-{0,1} encodings."""
        if self.is_classification:
            self._check_binary_labels(
                y,
                hint=" (use OpRandomForestClassifier / "
                "OpDecisionTreeClassifier for multiclass)",
            )

    def _fit_native(self, X, y, w, edges, bins=None) -> Optional[Any]:
        """C++ boosting path (native/txtrees.cpp tx_fit_gbt_hist); same
        init margin / loss / Newton leaf values as the JAX scan below.
        ``bins`` lets CV callers share one binning pass across folds."""
        p = self.params
        n = len(y)
        y32 = np.asarray(y, np.float32)
        wsum = max(float(w.sum()), 1e-12)
        if self.is_classification:
            pbar = float(np.clip((w * y32).sum() / wsum, 1e-6, 1 - 1e-6))
            f0 = float(np.log(pbar / (1.0 - pbar)))
        else:
            f0 = float((w * y32).sum() / wsum)
        max_depth = effective_max_depth(
            int(p["max_depth"]), n, float(p["min_instances_per_node"]),
            X.shape[1], int(p["max_bins"]), 4,
            cap=str(p.get("depth_cap", "auto")),
        )
        if bins is None:
            bins = bin_data(np.asarray(X, np.float32), edges)
        heaps = native_trees.fit_gbt(
            bins, y32, w,
            num_trees=int(p["num_trees"]), max_depth=max_depth,
            max_bins=int(p["max_bins"]),
            is_classification=self.is_classification,
            step_size=float(p["step_size"]), f0=f0,
            min_instances_per_node=float(p["min_instances_per_node"]),
            min_info_gain=float(p["min_info_gain"]),
        )
        if heaps is None:
            return None
        return {
            "edges": edges,
            "heaps": heaps,
            "f0": f0,
            "max_depth": max_depth,
            "step_size": float(p["step_size"]),
        }

    def fit_arrays(self, X, y, w=None) -> Any:
        self._check_labels(y)
        n, d = X.shape
        p = self.params
        w = np.ones(n, dtype=np.float32) if w is None else np.asarray(w, np.float32)
        edges = _sampled_bin_edges(X, int(p["max_bins"]), int(p["seed"]))
        backend = _resolve_backend(str(p.get("backend", "auto")), n)
        if backend == "native":
            result = self._fit_native(X, y, w, edges)
            if result is not None:
                return result
        bins = jnp.asarray(
            _bins_cast(_bin_for_backend(X, edges), int(p["max_bins"]))
        )
        yj = jnp.asarray(y, jnp.float32)
        wj = jnp.asarray(w)
        T = int(p["num_trees"])
        lr = float(p["step_size"])
        max_depth = effective_max_depth(
            int(p["max_depth"]), n, float(p["min_instances_per_node"]),
            d, int(p["max_bins"]), 4, cap=str(p.get("depth_cap", "auto")),
        )
        max_bins = int(p["max_bins"])
        # one-fold ride through the chunked boosting kernel: the margin-
        # carried host chunking keeps each device program under the
        # runtime watchdog (tree_kernel.fits_per_dispatch), and the
        # channel semantics live in one place ([w, wg, wgg, wh] stats,
        # Friedman variance impurity, Newton leaf sum(wg)/sum(wh))
        f0s, heaps = fit_gbt_folds(
            bins, yj, wj[None, :],
            num_trees=T, max_depth=max_depth, max_bins=max_bins,
            is_classification=self.is_classification,
            step_size=jnp.asarray(lr),
            min_instances_per_node=jnp.asarray(
                float(p["min_instances_per_node"])),
            min_info_gain=jnp.asarray(float(p["min_info_gain"])),
        )
        return {
            "edges": edges,
            "heaps": tuple(np.asarray(h[0]) for h in heaps),
            "f0": float(np.asarray(f0s)[0]),
            "max_depth": max_depth,
            "step_size": lr,
        }

    def _gbt_depth(self, n: int, d: int) -> int:
        p = self.params
        return effective_max_depth(
            int(p["max_depth"]), n, float(p["min_instances_per_node"]),
            d, int(p["max_bins"]), 4, cap=str(p.get("depth_cap", "auto")),
        )

    def fit_arrays_folds(self, X, y, W) -> list:
        self._check_labels(y)
        """CV fan-out: one fold-vmapped boosting scan sharing the binning
        and the design matrix (folds are weight masks, like the forests).
        On the native host backend the C++ learner loops folds but still
        shares one binning pass."""
        n, d = X.shape
        p = self.params
        W = np.asarray(W, np.float32)
        edges = _sampled_bin_edges(X, int(p["max_bins"]), int(p["seed"]))
        backend = _resolve_backend(str(p.get("backend", "auto")), n)
        if backend == "native":
            bins_host = bin_data(np.asarray(X, np.float32), edges)
            out = []
            for f in range(len(W)):
                res = self._fit_native(X, y, W[f], edges, bins=bins_host)
                if res is None:
                    break
                out.append(res)
            if len(out) == len(W):
                return out
        depth = self._gbt_depth(n, d)
        # no host materialization here: a pallas-binned device matrix
        # passes straight through when no mesh resharding is needed
        bins_d, y_d, W_d, _ = _shard_fold_inputs(
            _bins_cast(_bin_for_backend(X, edges), int(p["max_bins"])),
            np.asarray(y, np.float32), W,
        )
        f0s, heaps = fit_gbt_folds(
            bins_d, y_d, W_d,
            num_trees=int(p["num_trees"]), max_depth=depth,
            max_bins=int(p["max_bins"]),
            is_classification=self.is_classification,
            step_size=jnp.asarray(float(p["step_size"])),
            min_instances_per_node=jnp.asarray(
                float(p["min_instances_per_node"])),
            min_info_gain=jnp.asarray(float(p["min_info_gain"])),
        )
        f0s = np.asarray(f0s)
        heaps = tuple(np.asarray(h) for h in heaps)  # [F, T, ...]
        return [
            {
                "edges": edges,
                "heaps": tuple(h[f] for h in heaps),
                "f0": float(f0s[f]),
                "max_depth": depth,
                "step_size": float(p["step_size"]),
            }
            for f in range(len(W))
        ]

    def fit_arrays_folds_grid(self, X, y, W, grid) -> Optional[list]:
        self._check_labels(y)
        """Whole-grid GBT CV: grid points sharing static shapes
        (num_trees, effective depth, max_bins) batch as one dispatch over
        a traced (step_size, min_instances, min_info_gain) axis - the GBT
        analog of the forest grid batching (reference trains all paramMap
        variants concurrently on its Future pool, OpValidator.scala:
        289-306).  None on the native host backend."""
        p0 = self.params
        if _resolve_backend(str(p0.get("backend", "auto")),
                            X.shape[0]) == "native":
            return None
        n, d = X.shape
        cands = [self.with_params(**pmap) for pmap in grid]
        groups: dict[tuple, list[int]] = {}
        for j, cand in enumerate(cands):
            p = cand.params
            depth = cand._gbt_depth(n, d)
            key = (depth, int(p["max_bins"]), int(p["num_trees"]),
                   int(p["seed"]))
            groups.setdefault(key, []).append(j)
        results: list = [None] * len(grid)
        # y/W are identical for every static-shape group: pad + place once
        # (only bins varies per group, via the edges)
        mesh = _tree_cv_mesh()
        y32 = np.asarray(y, np.float32)
        W32 = np.asarray(W, np.float32)
        if mesh is None:
            yj, W_d = jnp.asarray(y32), jnp.asarray(W32)
        else:
            yj, W_d = _place(y32, mesh, 0), _place(W32, mesh, 1)
        edges_cache: dict[tuple, np.ndarray] = {}
        for key, js in groups.items():
            depth, max_bins, num_trees, seed = key
            ekey = (max_bins, seed)
            if ekey not in edges_cache:
                edges_cache[ekey] = _sampled_bin_edges(X, max_bins, seed)
            edges = edges_cache[ekey]
            bins_raw = _bins_cast(_bin_for_backend(X, edges), max_bins)
            bins = (
                jnp.asarray(bins_raw) if mesh is None
                else _place(bins_raw, mesh, 0)
            )
            step_g = jnp.asarray(
                [float(cands[j].params["step_size"]) for j in js], jnp.float32)
            minipn_g = jnp.asarray(
                [float(cands[j].params["min_instances_per_node"])
                 for j in js], jnp.float32)
            minig_g = jnp.asarray(
                [float(cands[j].params["min_info_gain"]) for j in js],
                jnp.float32)
            f0s, heaps = fit_gbt_folds_grid(
                bins, yj, W_d, step_g, minipn_g, minig_g,
                num_trees=num_trees, max_depth=depth, max_bins=max_bins,
                is_classification=self.is_classification,
            )
            f0s = np.asarray(f0s)                      # [G', F]
            heaps = tuple(np.asarray(h) for h in heaps)  # [G', F, T, ...]
            for gi, j in enumerate(js):
                results[j] = [
                    {
                        "edges": edges,
                        "heaps": tuple(h[gi][f] for h in heaps),
                        "f0": float(f0s[gi][f]),
                        "max_depth": depth,
                        "step_size": float(cands[j].params["step_size"]),
                    }
                    for f in range(len(W))
                ]
        return results

    def fused_tree_plan(self, X, y, W, grid):
        """Fused-training seam for boosted trees (see
        _RandomForest.fused_tree_plan for the contract): one grid x fold
        boosting scan plus the predict mirror of the jax
        ``predict_arrays`` route, traceable inside the one-program jit.
        Raises ``ValueError`` naming the fallback reason."""
        self._check_labels(y)
        from .tree_kernel import (
            fits_per_dispatch,
            gbt_f0,
            gbt_grid_scan_core,
        )

        n, d = X.shape
        if _resolve_backend(str(self.params.get("backend", "auto")),
                            n) == "native":
            raise ValueError("native_backend")
        cands = [self.with_params(**pmap) for pmap in grid]
        keys_seen = set()
        for cand in cands:
            p = cand.params
            keys_seen.add((cand._gbt_depth(n, d), int(p["max_bins"]),
                           int(p["num_trees"]), int(p["seed"])))
        if len(keys_seen) > 1:
            raise ValueError("grid_shape_groups")
        depth, max_bins, num_trees, seed = next(iter(keys_seen))
        G, F = len(grid), len(W)
        if G * F * num_trees > fits_per_dispatch(depth, n, d, max_bins, 4):
            raise ValueError("dispatch_chunked")
        edges = _sampled_bin_edges(X, max_bins, seed)
        bins = _bins_cast(_bin_for_backend(X, edges), max_bins)
        arrays = {
            "bins": np.asarray(bins),
            "y32": np.asarray(y, np.float32),
            "w_rows": np.asarray(W, np.float32),
            "step_g": np.asarray(
                [float(c.params["step_size"]) for c in cands], np.float32),
            "minipn_g": np.asarray(
                [float(c.params["min_instances_per_node"]) for c in cands],
                np.float32),
            "minig_g": np.asarray(
                [float(c.params["min_info_gain"]) for c in cands],
                np.float32),
        }
        is_classification = self.is_classification

        step_host = arrays["step_g"]

        def fit(a):
            f0s = gbt_f0(a["y32"], a["w_rows"], is_classification)
            margins = jnp.broadcast_to(f0s[None, :, None], (G, F, n))
            _margins, heaps = gbt_grid_scan_core(
                a["bins"], a["y32"], a["w_rows"], margins,
                a["step_g"], a["minipn_g"], a["minig_g"],
                num_trees=num_trees, max_depth=depth, max_bins=max_bins,
                is_classification=is_classification,
            )
            return (f0s,) + tuple(heaps)

        def score(state, bins_v, f, gi):
            # the EXACT op sequence of predict_arrays' jax route per
            # (g, f): vmapped per-tree traversal + eager f32
            # contribution sum on device, then the f64 head on host
            # (numpy sigmoid, like predict_arrays) - bit-equal to the
            # per-candidate dispatches
            f0s, hf, ht, hl, hv = state

            def one_tree(ff, tt, ll, vv):
                out = predict_tree(bins_v, ff, tt, ll, vv, depth)
                return out[:, 1] / jnp.maximum(out[:, 3], 1e-12)

            contribs = jax.vmap(one_tree)(
                hf[gi, f], ht[gi, f], hl[gi, f], hv[gi, f])
            Fm = float(f0s[f]) + float(step_host[gi]) * contribs.sum(
                axis=0)
            Fm = np.asarray(Fm, dtype=np.float64)
            if is_classification:
                return 1.0 / (1.0 + np.exp(-Fm))
            return Fm

        return {
            "arrays": arrays,
            "donate": ("w_rows",),
            "bins_key": "bins",
            "fit": fit,
            "n_state": 5,
            "score": score,
            "sig": ("gbt", depth, max_bins, G, F, num_trees,
                    is_classification),
        }

    def predict_arrays(self, params: Any, X: np.ndarray):
        bins = jnp.asarray(
            _bin_for_backend(np.asarray(X, np.float32), params["edges"])
        )
        hf, ht, hl, hv = (jnp.asarray(h) for h in params["heaps"])
        max_depth = params["max_depth"]

        def one(f, t, l, v):
            out = predict_tree(bins, f, t, l, v, max_depth)
            return out[:, 1] / jnp.maximum(out[:, 3], 1e-12)

        contribs = jax.vmap(one)(hf, ht, hl, hv)  # [T, n]
        F = params["f0"] + params["step_size"] * contribs.sum(axis=0)
        F = np.asarray(F, dtype=np.float64)
        if self.is_classification:
            p1 = 1.0 / (1.0 + np.exp(-F))
            prob = np.stack([1.0 - p1, p1], axis=1)
            raw = np.stack([-F, F], axis=1)
            return (p1 > 0.5).astype(np.float64), raw, prob
        return F, None, None

    def predict_arrays_np(self, params: Any, X: np.ndarray):
        # batch-first serving path: the old per-tree python loop paid
        # T x max_depth numpy dispatches per call (milliseconds at
        # batch-of-1); the vectorized traversal walks all T trees as one
        # [T, n] frontier (see tree_kernel.predict_forest_stats_np)
        bins = bin_data(np.asarray(X, np.float32), params["edges"])
        stats = predict_forest_stats_np(bins, params["heaps"],
                                        params["max_depth"])  # [T, n, 4]
        # f64 accumulation: the f32 per-tree ratios sum in a batch-shape-
        # dependent pairwise order, which would break batch-of-1 vs
        # batch-of-N bit parity at ~1e-9 (pinned by tests/test_serving.py)
        contrib = (
            stats[..., 1].astype(np.float64)
            / np.maximum(stats[..., 3], 1e-12)
        )
        F = params["f0"] + params["step_size"] * contrib.sum(axis=0)
        if self.is_classification:
            p1 = 1.0 / (1.0 + np.exp(-F))
            prob = np.stack([1.0 - p1, p1], axis=1)
            raw = np.stack([-F, F], axis=1)
            return (p1 > 0.5).astype(np.float64), raw, prob
        return F, None, None

    def predict_arrays_xla(self, params: Any, X):
        """jax-traceable mirror of the GBT ``predict_arrays_np``: binned
        gather traversal, f64 leaf-contribution accumulation in the same
        sequential tree order, then the logistic head (exp is the one op
        that may differ from libm by <=1 ULP)."""
        stats = _forest_stats_xla(
            _bin_xla(X, params["edges"]), params["heaps"],
            params["max_depth"],
        )
        contrib = (
            stats[..., 1].astype(jnp.float64)
            / jnp.maximum(stats[..., 3], jnp.float32(1e-12))
        )
        F = params["f0"] + params["step_size"] * _seq_sum0(contrib)
        if self.is_classification:
            p1 = 1.0 / (1.0 + jnp.exp(-F))
            prob = jnp.stack([1.0 - p1, p1], axis=1)
            raw = jnp.stack([-F, F], axis=1)
            return (p1 > 0.5).astype(jnp.float64), raw, prob
        return F, None, None

    def contributions(self, params: Any) -> Optional[np.ndarray]:
        """Impurity-decrease importances on the gradient-variance channels
        (Friedman gain) from the stored heaps - same contract as the
        forest path."""
        return heap_impurity_importances(
            params["heaps"], int(params["edges"].shape[0]), "variance"
        )


class OpGBTClassifier(_GBT):
    model_type = "OpGBTClassifier"
    is_classification = True


class OpGBTRegressor(_GBT):
    model_type = "OpGBTRegressor"
    is_classification = False


class OpXGBoostClassifier(OpGBTClassifier):
    """Hist-mode XGBoost-equivalent params surface (reference: core/src/main/
    scala/ml/dmlc/xgboost4j/.../XGBoostParams.scala shim); same boosted-tree
    kernel with XGBoost-flavored names and defaults (eta 0.3, numRound,
    gamma -> min split gain, minChildWeight -> min instances)."""

    model_type = "OpXGBoostClassifier"

    def __init__(self, num_round: int = 100, eta: float = 0.3,
                 gamma: float = 0.0, min_child_weight: float = 1.0,
                 **kw) -> None:
        kw.setdefault("max_depth", 6)
        kw.setdefault("min_info_gain", gamma)
        kw.setdefault("min_instances_per_node", min_child_weight)
        super().__init__(num_trees=num_round, step_size=eta, **kw)


class OpXGBoostRegressor(OpGBTRegressor):
    model_type = "OpXGBoostRegressor"

    def __init__(self, num_round: int = 100, eta: float = 0.3,
                 gamma: float = 0.0, min_child_weight: float = 1.0,
                 **kw) -> None:
        kw.setdefault("max_depth", 6)
        kw.setdefault("min_info_gain", gamma)
        kw.setdefault("min_instances_per_node", min_child_weight)
        super().__init__(num_trees=num_round, step_size=eta, **kw)
