"""Predictor stage bases.

Counterpart of the reference's OpPredictorWrapper / OpPredictionModel
machinery (reference: core/.../stages/sparkwrappers/specific/
OpPredictorWrapper.scala:67-90, SparkModelConverter.scala): a predictor
estimator takes (label RealNN, features OPVector) and produces a Prediction
column.  Unlike the reference - which wraps external Spark/JVM estimators
and calls their private predict methods reflectively per row - predictors
here implement two array-level methods and everything else is shared:

* ``fit_arrays(X, y, w) -> params`` - train on [n, d] + [n] (+ sample
  weights), jitted JAX;
* ``predict_arrays(params, X) -> (pred, raw, prob)`` - batched scoring.

Sample weights thread through every fit so splitter rebalancing
(DataBalancer) and CV fold membership are weight masks, not data copies -
that is what lets cross-validation fan out as one vmapped computation.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from ..stages.base import (
    PROB_SUFFIX,
    RAW_SUFFIX,
    Estimator,
    Lowering,
    Transformer,
    XlaLowering,
)
from ..types.columns import Column, NumericColumn, PredictionColumn, VectorColumn
from ..types.dataset import Dataset
from ..types.feature_types import OPVector, Prediction, RealNN


def _check_label_mask(label: NumericColumn, stage) -> None:
    """Missing labels must fail loudly at EVERY predictor fit - raw
    responses are gated at train() time, but a derived label (e.g. a
    string response through StringIndexer) reaches here with its own
    mask."""
    if not bool(label.mask.all()):
        n_bad = int((~label.mask).sum())
        raise ValueError(
            f"label input of {type(stage).__name__} ({stage.uid}) has "
            f"{n_bad} missing values; labels cannot be imputed - drop "
            "those rows before training"
        )


class PredictorModel(Transformer):
    """Fitted predictor: holds opaque params + the predict function."""

    input_types = [RealNN, OPVector]
    output_type = Prediction

    def __init__(self, estimator: "PredictorEstimator", params: Any, **kw) -> None:
        super().__init__(**kw)
        self.estimator_ref = estimator
        self.model_params = params
        self.holdout_metrics: Optional[dict] = None

    #: when True, scoring uses the estimator's pure-numpy predict path -
    #: set by the local scorer (see transmogrifai_tpu.local) to avoid
    #: device dispatch latency on per-record scoring
    prefer_numpy = False

    def transform_columns(self, cols: Sequence[Column], ds: Dataset) -> Column:
        vec = cols[-1]
        assert isinstance(vec, VectorColumn)
        predict = (
            self.estimator_ref.predict_arrays_np
            if self.prefer_numpy
            else self.estimator_ref.predict_arrays
        )
        pred, raw, prob = predict(
            self.model_params, np.asarray(vec.values, dtype=np.float64)
        )
        return PredictionColumn(pred, raw, prob)

    # interpretability hooks (reference: ModelInsights contributions)
    def feature_contributions(self) -> Optional[np.ndarray]:
        return self.estimator_ref.contributions(self.model_params)

    def lower(self) -> Optional[Lowering]:
        """Compile the fitted head to one closed-over array call through
        the estimator family's pure-numpy predict path.  Gated on the
        family's ``lowerable`` opt-in: a head whose predict dispatches
        to device state or is otherwise impure must stay interpreted."""
        est = self.estimator_ref
        if not getattr(est, "lowerable", False):
            return None
        vec_name = self.input_features[-1].name
        out = self.output_name
        params = self.model_params
        # the interpreted path feeds predict float64; families whose
        # kernel is float32-exact (trees: the first predict step is a
        # float32 binning, and f32->f64->f32 is the identity) skip the
        # float64 round trip without changing a single output bit
        in_dtype = (
            np.float32 if getattr(est, "predict_f32_exact", False)
            else np.float64
        )

        def fn(env: dict) -> dict:
            pred, raw, prob = est.predict_arrays_np(
                params, np.asarray(env[vec_name], dtype=in_dtype)
            )
            # PredictionColumn's canonical shapes: pred flat float64,
            # raw/prob [n, k] float64
            res = {out: np.asarray(pred, dtype=np.float64).reshape(-1)}
            if raw is not None:
                raw = np.asarray(raw, dtype=np.float64)
                res[out + RAW_SUFFIX] = (
                    raw[:, None] if raw.ndim == 1 else raw
                )
            if prob is not None:
                prob = np.asarray(prob, dtype=np.float64)
                res[out + PROB_SUFFIX] = (
                    prob[:, None] if prob.ndim == 1 else prob
                )
            return res

        # raw/prob presence is fixed by the fitted family, not the batch,
        # but it is not knowable here without running predict - so only
        # the guaranteed key is DECLARED.  A future stage consuming
        # out@raw/out@prob therefore fails with a compile-time
        # FusionError (interpreted fallback, correct results) instead of
        # compiling cleanly and KeyError-ing on every serve-time batch;
        # the result assembler reads the suffixed keys tolerantly via
        # env.get, so emitting undeclared keys is fine.
        return Lowering(
            fn=fn, inputs=(vec_name,),
            outputs=(out,),
            signature={out: "float64[n]"},
        )

    def lower_xla(self) -> Optional[XlaLowering]:
        """Compile the fitted head to a jax-traceable call through the
        family's ``predict_arrays_xla`` mirror of its numpy predict
        path.  Gated on both the ``lowerable`` opt-in and the family
        actually providing the jnp mirror - a family without one keeps
        the whole pipeline on the numpy-fused path."""
        import jax.numpy as jnp  # deferred: models import before jax use

        est = self.estimator_ref
        predict_xla = getattr(est, "predict_arrays_xla", None)
        if not getattr(est, "lowerable", False) or predict_xla is None:
            return None
        vec_name = self.input_features[-1].name
        out = self.output_name
        params = self.model_params
        in_dtype = (
            jnp.float32 if getattr(est, "predict_f32_exact", False)
            else jnp.float64
        )

        def fn(env: dict) -> dict:
            pred, raw, prob = predict_xla(
                params, env[vec_name].astype(in_dtype)
            )
            res = {out: pred.astype(jnp.float64).reshape(-1)}
            if raw is not None:
                raw = raw.astype(jnp.float64)
                res[out + RAW_SUFFIX] = (
                    raw[:, None] if raw.ndim == 1 else raw
                )
            if prob is not None:
                prob = prob.astype(jnp.float64)
                res[out + PROB_SUFFIX] = (
                    prob[:, None] if prob.ndim == 1 else prob
                )
            return res

        # only the guaranteed key is DECLARED (the numpy lower() has the
        # same contract and rationale); the traced program's actual
        # output set - raw/prob included when the family emits them - is
        # discovered at trace time and recorded in the executable cache
        return XlaLowering(
            fn=fn, inputs=(vec_name,),
            outputs=(out,),
            signature={out: "float64[n]"},
        )


class PredictorEstimator(Estimator):
    """Base estimator over (label, features)."""

    input_types = [RealNN, OPVector]
    output_type = Prediction
    model_type: str = "Predictor"
    #: opt-in to whole-pipeline fused compilation (local/fused.py): True
    #: promises ``predict_arrays_np`` is a pure host-side function of
    #: (params, X) safe to close over in a per-shape-bucket program
    lowerable: bool = False
    # Whether fit_arrays_batched's kernel assumes y in {0,1} (sigmoid/hinge
    # losses).  Classifiers keep the conservative True so multiclass labels
    # fall back to the per-candidate OVR route; regressors override to False
    # so continuous y never knocks them off the batched (MXU-packed) path
    # and never pays an np.unique scan over the full label column.
    batched_needs_binary_y: bool = True

    def _check_binary_labels(self, y, hint: str = "") -> None:
        """Binary-loss kernels (hinge, logistic boosting) must fail
        loudly on labels they cannot represent - >2 classes OR values
        outside {0,1} (y in {1,2} passes a count-only check yet maps both
        classes to the positive hinge side).  Device-resident labels skip
        the scan: the validator pre-guards its batched dispatches, and
        pulling a (possibly mesh-sharded) label column to host would
        block dispatch at 10M-row scale."""
        import jax

        if isinstance(y, jax.Array):
            return
        vals = np.unique(np.asarray(y))
        if len(vals) > 2:
            raise ValueError(
                f"{self.model_type} supports only binary classification; "
                f"the label column has {len(vals)} classes{hint}"
            )
        if len(vals) and not np.isin(vals, (0.0, 1.0)).all():
            raise ValueError(
                f"{self.model_type} expects labels in {{0, 1}}; got "
                f"values {vals.tolist()} (index the label first)"
            )

    def fit_arrays(
        self, X: np.ndarray, y: np.ndarray, w: Optional[np.ndarray] = None
    ) -> Any:
        raise NotImplementedError

    def predict_arrays(self, params: Any, X: np.ndarray):
        raise NotImplementedError

    def predict_arrays_np(self, params: Any, X: np.ndarray):
        """Pure-numpy scoring path for engine-free local serving (the analog
        of the reference's MLeap conversion, local/.../OpWorkflowModelLocal.
        scala:79).  Subclasses whose ``predict_arrays`` dispatches to JAX
        override this; the default assumes predict is already host-side."""
        return self.predict_arrays(params, X)

    def contributions(self, params: Any) -> Optional[np.ndarray]:
        return None

    def hyper_params(self) -> dict:
        """Hyperparameters relevant to model selection grids."""
        return dict(self.params)

    def with_params(self, **hp) -> "PredictorEstimator":
        clone = self.copy()
        clone.params = dict(self.params)
        clone.params.update(hp)
        return clone

    def fit_model(self, cols: Sequence[Column], ds: Dataset):
        label, vec = cols
        assert isinstance(label, NumericColumn)
        assert isinstance(vec, VectorColumn)
        if len(label) == 0:
            raise ValueError("cannot fit on empty dataset")
        _check_label_mask(label, self)
        params = self.fit_arrays(
            np.asarray(vec.values, dtype=np.float64),
            np.asarray(label.values, dtype=np.float64),
        )
        return PredictorModel(self, params)
