"""Histogram-based decision-tree learning as jitted JAX computations.

The TPU-native replacement for the reference's tree stack - Spark MLlib's
RandomForest/GBT histogram aggregation and the JNI libxgboost path
(reference: core/.../impl/classification/OpRandomForestClassifier.scala,
OpGBTClassifier.scala, OpXGBoostClassifier.scala + xgboost4j dep,
core/build.gradle:27).  Design:

* features are pre-binned into ``max_bins`` quantile bins (int32 [n, d]) -
  the same trick Spark/XGBoost-hist use, but the per-level histogram build
  is ONE ``segment_sum`` scatter over all (row, feature) pairs on device;
* trees grow LEVEL-WISE with static shapes: level l has exactly 2^l node
  slots (empty nodes produce zero histograms and become leaves), so the
  whole fit jits with no dynamic control flow;
* a forest is ``vmap`` over per-tree bootstrap weights + feature masks;
  gradient boosting is ``lax.scan`` over sequential tree fits;
* trees are stored as flat binary heaps (feature, threshold-bin, is_leaf,
  leaf value per node) - prediction is max_depth gather steps, fully
  vectorized over rows.

Sample weights thread through everything (CV folds and balancing ride the
weight vector, like the linear models).
"""
from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# Watchdog-safe dispatch sizing
# ---------------------------------------------------------------------------
def _tree_fit_work(depth: int, n: int, d: int, max_bins: int,
                   n_stats: int) -> float:
    """Estimated device work units for ONE tree fit: the per-level
    (row, feature) scatter plus the exponentially-growing split search."""
    scatter = (depth + 1.0) * float(n) * d * (n_stats + 1.0)
    split = (2.0 ** depth) * d * max_bins * (6.0 * n_stats + 3.0)
    return scatter + split


def fits_per_dispatch(depth: int, n: int, d: int, max_bins: int,
                      n_stats: int) -> int:
    """How many tree fits may share one device program.

    The tunneled TPU runtime kills device programs that run longer than
    ~2 minutes ("TPU worker crashed or restarted" - observed twice on v5e
    2026-07-30: a 1800-fit depth-12 grid dispatch died at ~120 s).  The
    batched CV fan-outs therefore chunk on the host so each program stays
    well under that; results are bit-identical because trees/grid points/
    folds are independent (and boosting chunks carry the margin).
    ``TX_TREE_FITS_PER_DISPATCH`` overrides the cap directly;
    ``TX_TREE_DISPATCH_BUDGET_S`` adjusts the target seconds (default 30,
    calibrated at ~2e9 work units/s: 0.12-0.35 s per depth-12
    Titanic-width fit on v5e)."""
    override = int(os.environ.get("TX_TREE_FITS_PER_DISPATCH", "0"))
    if override > 0:
        return override
    budget_s = float(os.environ.get("TX_TREE_DISPATCH_BUDGET_S", "30"))
    rate = 2.0e9
    per_fit = _tree_fit_work(depth, n, d, max_bins, n_stats)
    return max(1, int(budget_s * rate / max(per_fit, 1.0)))


def _concat_heaps(parts: list, axis: int):
    if len(parts) == 1:
        return parts[0]
    return tuple(
        jnp.concatenate([p[i] for p in parts], axis=axis)
        for i in range(len(parts[0]))
    )


def quantile_bin_edges(X: np.ndarray, max_bins: int) -> np.ndarray:
    """Per-feature quantile edges [d, max_bins-1] (host, once per fit).
    Duplicate edges are allowed (empty bins); searchsorted keeps order."""
    qs = np.linspace(0.0, 1.0, max_bins + 1)[1:-1]
    edges = np.quantile(X, qs, axis=0).T  # [d, max_bins-1]
    return np.asarray(edges, dtype=np.float32)


def bin_data(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Assign bins [n, d] int32 via per-feature searchsorted (C++ kernel
    when available - native/txtrees.cpp tx_bin_data, same side='left'
    lower-bound semantics)."""
    X = np.asarray(X, np.float32)
    try:
        from . import native_trees

        out = native_trees.bin_data(X, edges)
        if out is not None:
            return out
    except Exception:
        pass
    n, d = X.shape
    out = np.empty((n, d), dtype=np.int32)
    for j in range(d):
        out[:, j] = np.searchsorted(edges[j], X[:, j], side="left")
    return out


def bins_device_dtype(max_bins: int):
    """Device dtype for the binned matrix: int8 when every bin id fits
    (max_bins <= 127; searchsorted can emit max_bins itself for
    right-of-last-edge values, still < 127) - the [n, d] bins read is a
    dominant HBM term of every level scan, and int8 carries it at 1/4 the
    traffic.  TX_TREE_BIN_DTYPE=int32 opts out."""
    if os.environ.get("TX_TREE_BIN_DTYPE", "").strip() == "int32":
        return jnp.int32
    return jnp.int8 if max_bins <= 127 else jnp.int32


def _level_hist(bins, node_of_row, stats_w, L: int, B: int):
    """Per-level histogram [L, d, B, C] by one segment_sum scatter over all
    (row, feature) pairs — segment id = ((node * d) + j) * B + bin.

    The scatter's [n, d, C] stats broadcast is row-chunked above ~2^27
    elements: at 10M x 39 x 3 the one-shot broadcast is a 4.7 GB
    intermediate per tree (observed as a 46 GB compile-time allocation
    under the fold vmap on a 16 GB v5e, 2026-07-30); chunks accumulate
    into the [L*d*B, C] histogram under lax.scan instead.

    TX_TREE_HIST_SCATTER_ELEMS (the cap) is read at TRACE time — it is
    baked into the jit cache for a given shape, so changing it mid-process
    needs jax.clear_caches() to take effect (it is a sizing/test hook, not
    a per-call knob).  Chunked accumulation sums float channels in
    per-block order; gini counts are exact, variance channels (wy, wyy)
    agree with the one-shot scatter up to f32 summation order."""
    n, d = bins.shape
    C = stats_w.shape[1]

    def block_hist(nr, bb, sw):
        # bins may arrive int8 (bins_device_dtype): the segment-id
        # arithmetic needs int32 range (L*d*B >> 127)
        seg = (nr[:, None] * d + jnp.arange(d)[None, :]) * B + bb.astype(
            jnp.int32
        )
        flat = jnp.broadcast_to(
            sw[:, None, :], (sw.shape[0], d, C)
        ).reshape(-1, C)
        return jax.ops.segment_sum(
            flat, seg.reshape(-1), num_segments=L * d * B
        )

    # default sized for the OBSERVED buffer-assignment behavior on v5e:
    # the compile-time HBM bound held ~57 live instances of the per-block
    # [F, block, d, C] broadcast across a depth-6 fit's level scans (one
    # 91.6 GB allocation at block=2^27/(d*C) under a 3-fold vmap), so the
    # per-block footprint must stay ~2 orders under the chip's 16 GB:
    # 2^23 elements x 4 B x F=3 x ~57 ~= 7.6 GB worst case.
    cap = int(os.environ.get("TX_TREE_HIST_SCATTER_ELEMS", 1 << 23))
    if n * d * C <= cap:
        return block_hist(node_of_row, bins, stats_w).reshape(L, d, B, C)
    block = max(1, cap // max(d * C, 1))
    n_blocks = -(-n // block)
    pad = n_blocks * block - n
    # padded rows carry zero stats -> no histogram contribution
    nr = jnp.pad(node_of_row, (0, pad))
    bb = jnp.pad(bins, ((0, pad), (0, 0)))
    sw = jnp.pad(stats_w, ((0, pad), (0, 0)))

    def body(acc, xs):
        nrb, bbb, swb = xs
        return acc + block_hist(nrb, bbb, swb), None

    acc0 = jnp.zeros((L * d * B, C), stats_w.dtype)
    acc, _ = jax.lax.scan(
        body, acc0,
        (nr.reshape(n_blocks, block), bb.reshape(n_blocks, block, d),
         sw.reshape(n_blocks, block, C)),
    )
    return acc.reshape(L, d, B, C)


def _impurity(stats: jnp.ndarray, kind: str) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-node impurity*weight and node weight from stat channels.

    stats [..., C]: C = 3 (w, wy, wyy) for variance; C = 1+K (w, wc...) for
    gini.  Returns (weighted_impurity [...], w [...])."""
    w = stats[..., 0]
    safe_w = jnp.maximum(w, 1e-12)
    if kind == "variance":
        mean = stats[..., 1] / safe_w
        imp = stats[..., 2] / safe_w - mean**2
    else:  # gini
        p = stats[..., 1:] / safe_w[..., None]
        imp = 1.0 - (p * p).sum(axis=-1)
    return imp * w, w


@partial(
    jax.jit,
    static_argnames=(
        "max_depth", "max_bins", "impurity_kind", "n_stats", "feature_subset_p"
    ),
)
def fit_tree(
    bins: jnp.ndarray,          # [n, d] int32
    stats_row: jnp.ndarray,     # [n, C] per-row stat channels (already weighted)
    w_row: jnp.ndarray,         # [n] sample weights (0 = row not in this fit)
    feat_mask: jnp.ndarray,     # [d] bool - feature subset for this tree
    max_depth: int,
    max_bins: int,
    impurity_kind: str,
    n_stats: int,
    min_instances_per_node: float = 1.0,
    min_info_gain: float = 0.0,
    rng_key: jnp.ndarray | None = None,
    feature_subset_p: float = 1.0,
):
    """Grow one tree; returns heap arrays:
    feature [M] int32, thr_bin [M] int32, is_leaf [M] bool, value [M, C].
    M = 2^(max_depth+1) - 1; node children of i are 2i+1 / 2i+2."""
    n, d = bins.shape
    C = n_stats
    M = 2 ** (max_depth + 1) - 1
    B = max_bins

    heap_feature = jnp.zeros((M,), dtype=jnp.int32)
    heap_thr = jnp.full((M,), B, dtype=jnp.int32)  # everything goes left
    heap_leaf = jnp.ones((M,), dtype=bool)
    heap_value = jnp.zeros((M, C), dtype=stats_row.dtype)

    node_of_row = jnp.zeros((n,), dtype=jnp.int32)  # local index within level
    stats_w = stats_row * w_row[:, None]  # [n, C]

    for level in range(max_depth + 1):
        L = 2**level
        base = L - 1  # heap offset of this level
        # ---- histograms: scatter all (row, feature) pairs --------------
        hist = _level_hist(bins, node_of_row, stats_w, L, B)

        node_stats = hist[:, 0, :, :].sum(axis=1)  # [L, C] total per node
        node_imp, node_w = _impurity(node_stats, impurity_kind)
        heap_value = heap_value.at[base : base + L].set(node_stats)

        if level == max_depth:
            break

        # ---- split search ---------------------------------------------
        left = jnp.cumsum(hist, axis=2)             # [L, d, B, C]
        total = node_stats[:, None, None, :]
        right = total - left
        left_imp, left_w = _impurity(left, impurity_kind)
        right_imp, right_w = _impurity(right, impurity_kind)
        gain = (node_imp[:, None, None] - left_imp - right_imp) / jnp.maximum(
            node_w[:, None, None], 1e-12
        )
        level_mask = feat_mask[None, :]
        if rng_key is not None and feature_subset_p < 1.0:
            # per-NODE random feature subsets (Spark RF selects a subset per
            # node; Bernoulli(k/d) approximates choose-k-without-replacement)
            lk = jax.random.fold_in(rng_key, level)
            # p pinned to f32: bernoulli draws its uniforms in p's
            # canonical dtype, so a python-float p under an enable_x64
            # trace (the fused training programs) would sample DIFFERENT
            # f64 uniforms and grow different trees than the plain trace
            level_mask = level_mask & jax.random.bernoulli(
                lk, jnp.float32(feature_subset_p), (L, d)
            )
        valid = (
            level_mask[:, :, None]
            & (left_w >= min_instances_per_node)
            & (right_w >= min_instances_per_node)
        )
        gain = jnp.where(valid, gain, -jnp.inf)
        flat_gain = gain.reshape(L, d * B)
        best_flat = jnp.argmax(flat_gain, axis=1)                   # [L]
        best_gain = jnp.take_along_axis(flat_gain, best_flat[:, None], 1)[:, 0]
        best_feat = (best_flat // B).astype(jnp.int32)
        best_bin = (best_flat % B).astype(jnp.int32)

        splittable = (best_gain >= min_info_gain) & jnp.isfinite(best_gain)
        heap_feature = heap_feature.at[base : base + L].set(
            jnp.where(splittable, best_feat, 0)
        )
        heap_thr = heap_thr.at[base : base + L].set(
            jnp.where(splittable, best_bin, B)
        )
        heap_leaf = heap_leaf.at[base : base + L].set(~splittable)

        # ---- route rows -----------------------------------------------
        row_feat = best_feat[node_of_row]                 # [n]
        row_bin = jnp.take_along_axis(bins, row_feat[:, None], 1)[:, 0]
        row_split = splittable[node_of_row]
        go_right = row_split & (row_bin > best_bin[node_of_row])
        # rows under an already-leaf node keep going "left" into a shadow
        # child that inherits the parent stats -> harmless (prediction
        # stops at the first is_leaf node on the path)
        node_of_row = node_of_row * 2 + go_right.astype(jnp.int32)

    return heap_feature, heap_thr, heap_leaf, heap_value


@partial(jax.jit, static_argnames=("max_depth",))
def predict_tree(
    bins: jnp.ndarray,        # [n, d]
    heap_feature: jnp.ndarray,
    heap_thr: jnp.ndarray,
    heap_leaf: jnp.ndarray,
    heap_value: jnp.ndarray,  # [M, C]
    max_depth: int,
):
    """Traverse: n rows x max_depth gathers -> node stats [n, C]."""
    n = bins.shape[0]
    idx = jnp.zeros((n,), dtype=jnp.int32)
    for _ in range(max_depth):
        f = heap_feature[idx]
        t = heap_thr[idx]
        leaf = heap_leaf[idx]
        row_bin = jnp.take_along_axis(bins, f[:, None], 1)[:, 0]
        nxt = idx * 2 + 1 + (row_bin > t).astype(jnp.int32)
        idx = jnp.where(leaf, idx, nxt)
    return heap_value[idx]


# ---------------------------------------------------------------------------
# Forest = vmap over trees; fit + predict batched
# ---------------------------------------------------------------------------
def _fit_forest_core(
    bins, stats_row, w_row,
    boot_w,       # [T, n] bootstrap weights per tree
    feat_masks,   # [T, d]
    rng_keys,     # [T, 2] uint32 per-tree keys
    max_depth: int, max_bins: int, impurity_kind: str, n_stats: int,
    min_instances_per_node=1.0,
    min_info_gain=0.0,
    feature_subset_p: float = 1.0,
):
    def one(args):
        bw, fm, key = args
        return fit_tree(
            bins, stats_row, w_row * bw, fm,
            max_depth, max_bins, impurity_kind, n_stats,
            min_instances_per_node, min_info_gain,
            rng_key=key, feature_subset_p=feature_subset_p,
        )

    # lax.map (sequential trees, one trace) instead of vmap: a vmapped
    # histogram build materializes [T, 2^depth, d, bins, C] at the deepest
    # level, which exceeds HBM for deep forests; per-tree peak is
    # [2^depth, d, bins, C] and trees stream through it.
    return jax.lax.map(one, (boot_w, feat_masks, rng_keys))


_fit_forest_jit = partial(
    jax.jit,
    static_argnames=(
        "max_depth", "max_bins", "impurity_kind", "n_stats", "feature_subset_p"
    ),
)(_fit_forest_core)


def fit_forest(
    bins, stats_row, w_row, boot_w, feat_masks, rng_keys,
    max_depth: int, max_bins: int, impurity_kind: str, n_stats: int,
    min_instances_per_node: float = 1.0,
    min_info_gain: float = 0.0,
    feature_subset_p: float = 1.0,
):
    """Forest fit, host-chunked over trees so one device program stays
    under the runtime watchdog (see fits_per_dispatch)."""
    T = boot_w.shape[0]
    n, d = bins.shape
    cap = fits_per_dispatch(max_depth, n, d, max_bins, n_stats)
    parts = []
    for t0 in range(0, T, cap):
        t1 = min(t0 + cap, T)
        parts.append(_fit_forest_jit(
            bins, stats_row, w_row,
            boot_w[t0:t1], feat_masks[t0:t1], rng_keys[t0:t1],
            max_depth=max_depth, max_bins=max_bins,
            impurity_kind=impurity_kind, n_stats=n_stats,
            min_instances_per_node=min_instances_per_node,
            min_info_gain=min_info_gain,
            feature_subset_p=feature_subset_p,
        ))
    return _concat_heaps(parts, axis=0)


def _fit_forest_folds_core(
    bins, stats_row, w_rows, boot_w, feat_masks, rng_keys,
    max_depth: int, max_bins: int, impurity_kind: str, n_stats: int,
    min_instances_per_node=1.0,
    min_info_gain=0.0,
    feature_subset_p: float = 1.0,
):
    def one_fold(w):
        return _fit_forest_core(
            bins, stats_row, w, boot_w, feat_masks, rng_keys,
            max_depth, max_bins, impurity_kind, n_stats,
            min_instances_per_node, min_info_gain, feature_subset_p,
        )

    return jax.vmap(one_fold)(w_rows)


_fit_forest_folds_jit = partial(
    jax.jit,
    static_argnames=(
        "max_depth", "max_bins", "impurity_kind", "n_stats", "feature_subset_p"
    ),
)(_fit_forest_folds_core)


def fit_forest_folds(
    bins, stats_row, w_rows,  # w_rows [F, n]: one weight vector per CV fold
    boot_w, feat_masks, rng_keys,
    max_depth: int, max_bins: int, impurity_kind: str, n_stats: int,
    min_instances_per_node=1.0,
    min_info_gain=0.0,
    feature_subset_p: float = 1.0,
):
    """CV fan-out for forests: folds ride the weight axis exactly like the
    linear models' vmapped Newton fits - binning and the design matrix are
    shared, only the [F, n] weight masks differ.  (Replaces the reference's
    per-fold Spark jobs, OpValidator.scala:289-306.)  Host-chunked over
    trees so F x T' fits per device program stay under the watchdog."""
    F = w_rows.shape[0]
    T = boot_w.shape[0]
    n, d = bins.shape
    cap = fits_per_dispatch(max_depth, n, d, max_bins, n_stats)
    t_cap = max(1, cap // max(F, 1))
    parts = []
    for t0 in range(0, T, t_cap):
        t1 = min(t0 + t_cap, T)
        parts.append(_fit_forest_folds_jit(
            bins, stats_row, w_rows,
            boot_w[t0:t1], feat_masks[t0:t1], rng_keys[t0:t1],
            max_depth=max_depth, max_bins=max_bins,
            impurity_kind=impurity_kind, n_stats=n_stats,
            min_instances_per_node=min_instances_per_node,
            min_info_gain=min_info_gain,
            feature_subset_p=feature_subset_p,
        ))
    return _concat_heaps(parts, axis=1)


def _fit_forest_folds_grid_core(
    bins, stats_row, w_rows, boot_w, feat_masks, rng_keys,
    min_instances_g, min_info_gain_g,  # [G] per-grid-point TRACED scalars
    max_depth: int, max_bins: int, impurity_kind: str, n_stats: int,
    feature_subset_p: float = 1.0,
):
    def one_cfg(args):
        minipn, minig = args
        return _fit_forest_folds_core(
            bins, stats_row, w_rows, boot_w, feat_masks, rng_keys,
            max_depth, max_bins, impurity_kind, n_stats,
            minipn, minig, feature_subset_p,
        )

    # sequential over grid points (lax.map), vmapped over folds inside:
    # peak memory stays at one fold-batch of level histograms
    return jax.lax.map(one_cfg, (min_instances_g, min_info_gain_g))


_fit_forest_folds_grid_jit = partial(
    jax.jit,
    static_argnames=(
        "max_depth", "max_bins", "impurity_kind", "n_stats", "feature_subset_p"
    ),
)(_fit_forest_folds_grid_core)


def fit_forest_folds_grid(
    bins, stats_row, w_rows,      # w_rows [F, n] fold weights
    boot_w, feat_masks, rng_keys,
    min_instances_g, min_info_gain_g,  # [G] per-grid-point TRACED scalars
    max_depth: int, max_bins: int, impurity_kind: str, n_stats: int,
    feature_subset_p: float = 1.0,
):
    """Grid x fold forest fan-out.

    min_instances_per_node / min_info_gain are traced scalars in fit_tree,
    so every grid point sharing the static shape params (depth, bins,
    trees, subset strategy) batches along a sequential lax.map axis over
    the fold-vmapped fit - a 16-config RF grid x 3 folds compiles once
    instead of 16 host-loop iterations (reference counterpart: the Future
    pool training all paramMap variants concurrently,
    OpValidator.scala:289-306).  The G x F x T fit product is host-chunked
    over grid points (and, for deep trees, over trees) so each device
    program stays under the runtime watchdog.  Returns heaps with leading
    axes [G, F, T, ...]."""
    G = int(min_instances_g.shape[0])
    F = w_rows.shape[0]
    T = boot_w.shape[0]
    n, d = bins.shape
    cap = fits_per_dispatch(max_depth, n, d, max_bins, n_stats)
    if F * T <= cap:
        g_cap = max(1, cap // max(F * T, 1))
        parts = []
        for g0 in range(0, G, g_cap):
            g1 = min(g0 + g_cap, G)
            parts.append(_fit_forest_folds_grid_jit(
                bins, stats_row, w_rows, boot_w, feat_masks, rng_keys,
                min_instances_g[g0:g1], min_info_gain_g[g0:g1],
                max_depth=max_depth, max_bins=max_bins,
                impurity_kind=impurity_kind, n_stats=n_stats,
                feature_subset_p=feature_subset_p,
            ))
        return _concat_heaps(parts, axis=0)
    # deep/expensive trees: one grid point at a time, trees chunked inside
    g_parts = []
    for g in range(G):
        heaps = fit_forest_folds(
            bins, stats_row, w_rows, boot_w, feat_masks, rng_keys,
            max_depth=max_depth, max_bins=max_bins,
            impurity_kind=impurity_kind, n_stats=n_stats,
            min_instances_per_node=min_instances_g[g],
            min_info_gain=min_info_gain_g[g],
            feature_subset_p=feature_subset_p,
        )
        g_parts.append(tuple(h[None] for h in heaps))
    return _concat_heaps(g_parts, axis=0)


@partial(jax.jit, static_argnames=("is_classification",))
def _gbt_f0(y, w_rows, is_classification: bool):
    """Per-fold initial margin [F] (weighted base rate / mean)."""
    wsum = jnp.maximum(w_rows.sum(axis=1), 1e-12)
    ybar = (w_rows * y[None, :]).sum(axis=1) / wsum
    if is_classification:
        pbar = jnp.clip(ybar, 1e-6, 1 - 1e-6)
        return jnp.log(pbar / (1.0 - pbar))
    return ybar


def _gbt_folds_scan_core(
    bins, y, w_rows, margins,  # margins [F, n]: boosting state carried in
    num_trees: int, max_depth: int, max_bins: int, is_classification: bool,
    step_size, min_instances_per_node, min_info_gain,  # traced scalars
):
    n, d = bins.shape
    feat_mask = jnp.ones((d,), dtype=bool)

    def one_fold(w, m):
        def body(F, _):
            if is_classification:
                pr = jax.nn.sigmoid(F)
                g = y - pr
                h = jnp.maximum(pr * (1.0 - pr), 1e-6)
            else:
                g = y - F
                h = jnp.ones_like(g)
            stats = jnp.stack([jnp.ones_like(g), g, g * g, h], axis=1)
            heap = fit_tree(
                bins, stats, w, feat_mask,
                max_depth, max_bins, "variance", 4,
                min_instances_per_node, min_info_gain,
            )
            hf, ht, hl, hv = heap
            out = predict_tree(bins, hf, ht, hl, hv, max_depth)
            leaf_val = out[:, 1] / jnp.maximum(out[:, 3], 1e-12)
            return F + step_size * leaf_val, heap

        return jax.lax.scan(body, m, None, length=num_trees)

    return jax.vmap(one_fold)(w_rows, margins)


_gbt_folds_scan = partial(
    jax.jit,
    static_argnames=("num_trees", "max_depth", "max_bins", "is_classification"),
)(_gbt_folds_scan_core)


def fit_gbt_folds(
    bins, y, w_rows,           # w_rows [F, n]: one weight vector per CV fold
    num_trees: int, max_depth: int, max_bins: int, is_classification: bool,
    step_size, min_instances_per_node, min_info_gain,  # traced scalars
):
    """GBT CV fan-out: folds ride the weight axis through the boosting
    scan, exactly like fit_forest_folds - binning and the design matrix
    are shared, only the [F, n] fold masks differ.  step_size /
    min_instances / min_info_gain are traced, so grid points sharing the
    static shape params (num_trees, depth, bins) can batch over them too
    (fit_gbt_folds_grid).  The sequential boosting scan is host-chunked
    with the margin carried between chunks (bit-identical to one scan) so
    each device program stays under the runtime watchdog.  Returns
    (f0 [F], heaps with leading [F, T])."""
    F = w_rows.shape[0]
    n, d = bins.shape
    y = jnp.asarray(y, jnp.float32)
    f0s = _gbt_f0(y, w_rows, is_classification)
    cap = fits_per_dispatch(max_depth, n, d, max_bins, 4)
    t_cap = max(1, cap // max(F, 1))
    margins = jnp.broadcast_to(f0s[:, None], (F, n))
    parts = []
    for t0 in range(0, num_trees, t_cap):
        ln = min(t_cap, num_trees - t0)
        margins, heaps = _gbt_folds_scan(
            bins, y, w_rows, margins,
            num_trees=ln, max_depth=max_depth, max_bins=max_bins,
            is_classification=is_classification,
            step_size=step_size,
            min_instances_per_node=min_instances_per_node,
            min_info_gain=min_info_gain,
        )
        parts.append(heaps)
    return f0s, _concat_heaps(parts, axis=1)


def _gbt_grid_scan_core(
    bins, y, w_rows, margins_g,  # margins_g [G, F, n]
    step_g, min_instances_g, min_info_gain_g,
    num_trees: int, max_depth: int, max_bins: int, is_classification: bool,
):
    def one_cfg(args):
        m_g, ss, mi, mg = args
        return _gbt_folds_scan_core(
            bins, y, w_rows, m_g, num_trees, max_depth, max_bins,
            is_classification, ss, mi, mg,
        )

    return jax.lax.map(
        one_cfg, (margins_g, step_g, min_instances_g, min_info_gain_g)
    )


_gbt_grid_scan = partial(
    jax.jit,
    static_argnames=("num_trees", "max_depth", "max_bins", "is_classification"),
)(_gbt_grid_scan_core)


def fit_gbt_folds_grid(
    bins, y, w_rows,
    step_g, min_instances_g, min_info_gain_g,  # [G] traced per-grid-point
    num_trees: int, max_depth: int, max_bins: int, is_classification: bool,
):
    """Grid x fold GBT fan-out: sequential lax.map over the traced grid
    scalars around the fold-vmapped boosting scan (same shape discipline
    as fit_forest_folds_grid), host-chunked over grid points and boosting
    segments (margins carried) to stay under the runtime watchdog.
    Returns (f0 [G, F], heaps with leading [G, F, T])."""
    G = int(step_g.shape[0])
    F = w_rows.shape[0]
    n, d = bins.shape
    y = jnp.asarray(y, jnp.float32)
    f0s = _gbt_f0(y, w_rows, is_classification)          # same for all g
    cap = fits_per_dispatch(max_depth, n, d, max_bins, 4)
    g_cap = max(1, cap // max(F * num_trees, 1))
    t_cap = max(1, cap // max(F, 1))
    g_parts = []
    for g0 in range(0, G, g_cap):
        g1 = min(g0 + g_cap, G)
        margins = jnp.broadcast_to(f0s[None, :, None], (g1 - g0, F, n))
        t_parts = []
        for t0 in range(0, num_trees, t_cap):
            ln = min(t_cap, num_trees - t0)
            margins, heaps = _gbt_grid_scan(
                bins, y, w_rows, margins,
                step_g[g0:g1], min_instances_g[g0:g1],
                min_info_gain_g[g0:g1],
                num_trees=ln, max_depth=max_depth, max_bins=max_bins,
                is_classification=is_classification,
            )
            t_parts.append(heaps)
        g_parts.append(_concat_heaps(t_parts, axis=2))
    heaps = _concat_heaps(g_parts, axis=0)
    f0_gf = jnp.broadcast_to(f0s[None, :], (G, F))
    return f0_gf, heaps


#: fused-training seams (local/fused_train.py, ISSUE 15): the un-jitted
#: grid x fold fit cores, traceable INSIDE one fit->score->metrics
#: program so the [2^l, d, bins, C] histogram working set - the
#: memory-bound hot spot the _level_hist comments size - lives and dies
#: within a single jit whose per-call buffers (fold weights, bootstrap
#: weights, stat channels) arrive donated.  Bodies are shared with the
#: kernel-at-a-time jit wrappers above, so fused == chunked bit-for-bit
#: whenever one dispatch covers the whole G x F x T product.
fit_forest_folds_grid_core = _fit_forest_folds_grid_core
gbt_grid_scan_core = _gbt_grid_scan_core
gbt_f0 = _gbt_f0


def effective_max_depth(
    max_depth: int,
    n_rows: int,
    min_instances_per_node: float,
    n_features: int | None = None,
    max_bins: int | None = None,
    n_stats: int | None = None,
    cap: str = "auto",
) -> int:
    """Depth cap - default-on, overridable with ``cap="off"``.

    Two provably-lossless bounds (no expressible tree is excluded):

    * support: every split keeps >= min_instances rows in each child, so a
      root-to-leaf path peels off at least min_instances rows per level -
      no leaf sits deeper than n / min_instances even in a maximally
      unbalanced tree.  (A balanced-tree log2 bound would silently forbid
      the reference's winning Titanic config, RF maxDepth=12 on 891 rows -
      /root/reference/README.md:61-78.)
    * memory: cap depth so the split search's working set stays under
      TX_TREE_HIST_BYTES (default 4 GiB - a quarter of a v5e chip's HBM).
      Split search concurrently holds hist + its cumsum + the right-side
      complement (3 x [2^l, d, bins, C]) plus the left/right impurity and
      gain arrays (3 x [2^l, d, bins]) - but only up to level depth-1
      (fit_tree breaks before searching the final level), so a budget
      fitting 2^l nodes admits depth l+1.
    """
    md = max(1, int(max_depth))
    if cap == "off":
        return md
    m = max(float(min_instances_per_node), 1.0)
    support_cap = int(max(n_rows, 2) // m)
    caps = [md, max(1, support_cap)]
    if n_features and max_bins and n_stats:
        import os

        budget = float(os.environ.get("TX_TREE_HIST_BYTES", 1 << 32))
        per_node = 4.0 * n_features * max_bins * (3.0 * n_stats + 3.0)
        caps.append(int(np.floor(np.log2(max(budget / per_node, 2.0)))) + 1)
    return max(1, min(caps))


def _impurity_np(stats: np.ndarray, kind: str) -> np.ndarray:
    """Weighted impurity per node from stored heap stats (numpy mirror of
    _impurity): stats [..., C] with channel 0 = node weight."""
    w = stats[..., 0]
    safe_w = np.maximum(w, 1e-12)
    if kind == "variance":
        mean = stats[..., 1] / safe_w
        imp = stats[..., 2] / safe_w - mean**2
    else:  # gini
        p = stats[..., 1:] / safe_w[..., None]
        imp = 1.0 - (p * p).sum(axis=-1)
    return imp * w


def heap_impurity_importances(
    heaps: tuple, d: int, impurity_kind: str
) -> np.ndarray:
    """Impurity-decrease feature importances computed from stored heaps.

    The flat heap keeps full node stats at EVERY slot (heap_value), so the
    weighted impurity decrease of internal node i is
    imp_w(i) - imp_w(2i+1) - imp_w(2i+2) - no extra bookkeeping in the fit
    kernels (JAX or C++, both emit the same layout).  Aggregation follows
    Spark's featureImportances contract (reference: ModelInsights.scala:
    435-525 surfaces Spark's treeModels featureImportances): accumulate
    gain x node-weight per split feature, normalize per tree, average over
    trees, normalize.
    """
    hf, ht, hl, hv = (np.asarray(h) for h in heaps)
    if hf.ndim == 1:  # single tree -> add tree axis
        hf, ht, hl, hv = hf[None], ht[None], hl[None], hv[None]
    T, M = hf.shape
    n_inner = (M - 1) // 2  # nodes with children inside the heap
    imp = _impurity_np(hv, impurity_kind)            # [T, M]
    parents = np.arange(n_inner)
    decrease = (
        imp[:, parents]
        - imp[:, 2 * parents + 1]
        - imp[:, 2 * parents + 2]
    )
    # Reachability gate: rows under an already-leaf node keep flowing into
    # a "shadow" left child that inherits the parent's stats; with per-node
    # random feature subsets such a shadow node can later pass the gain
    # gate and be marked internal even though prediction never reaches it.
    # Only splits on the real tree may contribute.
    reach = np.zeros((T, M), dtype=bool)
    reach[:, 0] = True
    for i in range(n_inner):
        ok = reach[:, i] & ~hl[:, i]
        reach[:, 2 * i + 1] |= ok
        reach[:, 2 * i + 2] |= ok
    internal = (~hl[:, :n_inner]) & reach[:, :n_inner]
    contrib = np.where(internal, np.maximum(decrease, 0.0), 0.0)  # [T, n_inner]
    per_tree = np.zeros((T, d))
    feats = np.clip(hf[:, :n_inner], 0, d - 1)
    for t in range(T):
        np.add.at(per_tree[t], feats[t][internal[t]], contrib[t][internal[t]])
    totals = per_tree.sum(axis=1, keepdims=True)
    normed = np.divide(
        per_tree, totals, out=np.zeros_like(per_tree), where=totals > 0
    )
    mean = normed.mean(axis=0)
    s = mean.sum()
    return mean / s if s > 0 else mean


def predict_forest_stats_np(bins, heaps, max_depth: int):
    """Vectorized pure-numpy traversal of EVERY tree at once -> raw leaf
    stats [T, n, C].

    The serving-critical fix (VERDICT r5 Weak #4): the per-tree python
    loop (T calls to predict_tree_np, each max_depth numpy dispatches on
    tiny arrays) cost ~6 ms/row on the 50-tree RF winner - interpreter
    and numpy-dispatch overhead, not arithmetic.  Walking all T trees as
    one [T, n] index frontier does max_depth x ~6 vectorized ops TOTAL,
    so batch-of-1 through the flat heap is microseconds.
    """
    hf, ht, hl, hv = (np.asarray(h) for h in heaps)
    n = bins.shape[0]
    T = hf.shape[0]
    rows = np.arange(n)[None, :]          # [1, n] broadcast over trees
    trees = np.arange(T)[:, None]         # [T, 1] broadcast over rows
    idx = np.zeros((T, n), dtype=np.int64)
    for _ in range(max_depth):
        f = hf[trees, idx]                # [T, n] split feature per node
        thr = ht[trees, idx]
        leaf = hl[trees, idx]
        row_bin = bins[rows, f]           # [T, n] gather bins[j, f[t, j]]
        nxt = idx * 2 + 1 + (row_bin > thr).astype(np.int64)
        idx = np.where(leaf, idx, nxt)
    return hv[trees, idx]                 # [T, n, C]


def predict_forest_np(bins, heaps, max_depth: int):
    """Numpy mirror of predict_forest: mean normalized per-tree stats
    [n, C-1] via the vectorized all-trees traversal."""
    stats = predict_forest_stats_np(bins, heaps, max_depth)
    w = np.maximum(stats[..., 0:1], 1e-12)
    return (stats[..., 1:] / w).mean(axis=0)


@partial(jax.jit, static_argnames=("max_depth",))
def predict_forest(bins, heaps, max_depth: int):
    """Average normalized per-tree outputs: [n, C-ish]."""
    hf, ht, hl, hv = heaps

    def one(f, t, l, v):
        out = predict_tree(bins, f, t, l, v, max_depth)
        w = jnp.maximum(out[:, 0:1], 1e-12)
        return out[:, 1:] / w  # normalized stats (probs or mean target)

    per_tree = jax.vmap(one)(hf, ht, hl, hv)  # [T, n, C-1]
    return per_tree.mean(axis=0)
