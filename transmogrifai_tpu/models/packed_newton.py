"""MXU-packed batched Newton kernels for the linear-model CV fan-out.

The CV fold x grid fan-out for LR / LinearSVC / LinearRegression was a
``vmap`` of the per-replica kernel: its FLOPs hot spot, the weighted Gram
``X^T diag(wt_b) X``, lowered to a [B, d, d] *batched* matmul whose d x d
output tiles (d ~ 39 after vectorization) use ~9% of the 128x128 MXU
(measured 0.45% MFU on a v5e, docs/performance.md).  These kernels are the
explicitly-batched rewrite: every replica-indexed op keeps the replica
axis B in a matmul's *N dimension*, so the machine sees a few LARGE
matmuls instead of B small ones:

* ``z = X @ Gamma^T``            [n, d] @ [d, B]      (tall)
* ``Xr = X^T @ resid``           [d, n] @ [n, B]      (wide-contraction)
* Gram: ``X^T @ Z``              [d, n] @ [n, B*d]    (packed, chunked)

where ``Z[:, b*d+j] = wt[:, b] * X[:, j]`` packs ALL replica weightings
into the N dimension - one matmul whose output tile rows are d/128 and
whose columns fill full 128-lanes, ~3x the utilization of the [B, d, d]
form.  Z is materialized in row chunks (``TX_PACKED_GRAM_ELEMS`` budget)
so the temporary never exceeds a few hundred MB regardless of n.

Replica-count note: B = folds x grid is 24 for the reference default LR
grid (DefaultSelectorParams.scala:36-61) - B*d ~ 936 columns, 7+ full MXU
lanes.

Multi-device composition (round 5): the row-chunk ``dynamic_slice`` scan
that fought GSPMD row sharding now runs INSIDE a ``shard_map`` body over
the mesh's 'data' axis - each device packs its LOCAL row shard (slicing is
shard-local, so the conflict disappears), then a single ``psum`` over
'data' reduces the [d, B_local*d] partials; with a 'replica' axis on the
mesh the B replicas shard too and the [B, d, d] Gram comes back
replica-sharded.  Every other op in these kernels is a plain matmul /
reduction that GSPMD shards the same way it shards the vmap kernels.  So
the v5e-8 CV fan-out shape (rows over 'data', fold x grid over 'replica',
the reference's Future-pool analog, OpValidator.scala:289-306) keeps MXU
packing instead of falling back to the [B, d, d] batched-matmul form.

Math per row is IDENTICAL to the vmapped per-replica kernels (same
standardization-folded algebra, same bf16-view / f32-accumulate Hessian
contract, same eps/jitter terms), so coefficients agree to f32 fixed-point
tolerance - pinned by tests/test_packed_newton.py, including the
sharded == unsharded parity cases on an 8-device CPU mesh.
"""
from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
try:  # jax >= 0.4.35 exports it at the top level
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def _gram_chunk_rows(n: int, B: int, d: int) -> int:
    """Rows per Gram chunk: bound the [c, B*d] packed temporary by an
    element budget (default 2^27 elements = 256 MiB bf16 / 512 MiB f32).
    Trace-time decision like _hessian_bf16: TX_PACKED_GRAM_ELEMS changes
    take effect for new (shape, dtype) compilations only - already-cached
    executables keep the budget they were traced with."""
    budget = int(os.environ.get("TX_PACKED_GRAM_ELEMS", 1 << 27))
    c = max(128, budget // max(B * d, 1))
    return min(n, c - (c % 8))


def _gram_2d(Xh, wt_nB):
    """Packed weighted Gram over the rows this function SEES: [d, B*d] f32
    with columns b*d+j holding X^T diag(wt[:, b]) X[:, j].  Row-chunked so
    the [c, B*d] packed temporary stays within the element budget; under
    shard_map the dynamic_slice indices are shard-local, so this same body
    serves both the single-device and the mesh route."""
    n, d = Xh.shape
    B = wt_nB.shape[1]
    c = _gram_chunk_rows(n, B, d)
    if c >= n:
        Z = (wt_nB[:, :, None] * Xh[:, None, :]).reshape(n, B * d)
        return jnp.matmul(Xh.T, Z, preferred_element_type=jnp.float32)
    nc = -(-n // c)
    pad = nc * c - n
    # zero rows in BOTH operands contribute exactly zero to the Gram
    Xp = jnp.pad(Xh, ((0, pad), (0, 0)))
    Wp = jnp.pad(wt_nB, ((0, pad), (0, 0)))

    def body(acc, i):
        Xc = jax.lax.dynamic_slice_in_dim(Xp, i * c, c)
        Wc = jax.lax.dynamic_slice_in_dim(Wp, i * c, c)
        Zc = (Wc[:, :, None] * Xc[:, None, :]).reshape(c, B * d)
        return (
            acc + jnp.matmul(Xc.T, Zc, preferred_element_type=jnp.float32),
            None,
        )

    G, _ = jax.lax.scan(
        body, jnp.zeros((d, B * d), jnp.float32), jnp.arange(nc)
    )
    return G


def packed_weighted_gram(Xh, wt_nB, mesh=None):
    """All-replica weighted Gram as packed matmuls: returns [B, d, d] f32
    with G[b] = X^T diag(wt[:, b]) X.

    Xh: [n, d] design matrix (bf16 view on TPU, f32 elsewhere - caller's
    choice; accumulation is always f32).  wt_nB: [n, B] per-replica row
    weights in the SAME dtype as Xh so the multiply stays in the matmul's
    input precision.

    ``mesh``: a Mesh with a 'data' axis routes through shard_map - each
    device packs its local rows, one psum('data') reduces the partial
    Grams, and a 'replica' axis (if present) keeps B sharded end to end.
    Requires n divisible by the data axis and B by the replica axis (the
    validator pads rows; cv_mesh_or_none picks replica | B).
    """
    if mesh is not None and "data" in mesh.axis_names:
        nd = mesh.shape["data"]
        nr = mesh.shape.get("replica", 1)
        if Xh.shape[0] % nd or wt_nB.shape[1] % nr:
            # mesh doesn't divide the shapes (direct caller, not the
            # validator's padded layout): let GSPMD lower the plain body
            mesh = None
    if mesh is not None:
        has_rep = "replica" in mesh.axis_names
        wt_spec = P("data", "replica") if has_rep else P("data", None)
        out_spec = (
            P("replica", None, None) if has_rep else P(None, None, None)
        )

        def local_gram(Xl, Wl):
            d = Xl.shape[1]
            Bl = Wl.shape[1]
            G = jax.lax.psum(_gram_2d(Xl, Wl), "data")
            return G.reshape(d, Bl, d).transpose(1, 0, 2)

        return shard_map(
            local_gram,
            mesh=mesh,
            in_specs=(P("data", None), wt_spec),
            out_specs=out_spec,
        )(Xh, wt_nB)
    d = Xh.shape[1]
    B = wt_nB.shape[1]
    return _gram_2d(Xh, wt_nB).reshape(d, B, d).transpose(1, 0, 2)


def packed_mesh_or_none(X, W=None):
    """The Mesh to run the packed Gram over, when an input is sharded over
    a mesh with a 'data' axis (the validator's device_put layout); None
    routes the caller to the vmap kernels / plain Gram body.

    Indivisible shapes return None too: X rows must divide the 'data'
    axis and W's replica count the 'replica' axis, or the shard_map body
    can't form - and the fallback (dynamic_slice row chunks under plain
    GSPMD row sharding) is exactly the layout conflict the vmap kernels
    exist to avoid, so such inputs must NOT take the packed route at all."""
    for a in (X, W):
        sh = getattr(a, "sharding", None)
        if (
            isinstance(sh, NamedSharding)
            and "data" in sh.mesh.axis_names
            and len(sh.mesh.devices.flat) > 1
        ):
            mesh = sh.mesh
            if X.shape[0] % mesh.shape["data"]:
                return None
            if W is not None and W.shape[0] % mesh.shape.get("replica", 1):
                return None
            return mesh
    return None


def use_packed(*arrays) -> bool:
    """Packed kernels are the TPU route (TX_PACKED_GRAM=0 forces the vmap
    path, =1 forces packed anywhere).  Mesh-sharded inputs ride the
    shard_map Gram (packed_mesh_or_none supplies the mesh); multi-device
    inputs sharded some OTHER way fall back to the vmap kernels.  CPU
    hosts also keep vmap: the packing trades a [c, B*d] temporary for MXU
    tile occupancy, a trade that MEASURED 0.5x on CPU (no MXU to feed;
    CPU_MICROBENCH.json lrpack section)."""
    override = os.environ.get("TX_PACKED_GRAM")
    if override is not None:
        return override.strip().lower() not in ("0", "false", "")
    try:
        if jax.default_backend() != "tpu":
            return False
    except Exception:
        return False
    multi = any(
        len(getattr(getattr(a, "sharding", None), "device_set", ())) > 1
        for a in arrays
    )
    return not multi or packed_mesh_or_none(*arrays) is not None


def run_packed_guarded(label: str, fit_fn, host_fit_fn, mesh):
    """Run a packed MESH fit under the collective watchdog
    (parallel/resilience): the shard_map Gram's psum is the collective a
    hung or dead peer wedges, so it rides a deadline derived from
    observed step times, gets one straggler retry, and shrinks to
    ``host_fit_fn`` - the same kernel on host-resident copies with
    mesh=None (the single-host route) - when a peer is gone.  No-mesh /
    single-device calls bypass the guard entirely: the healthy hot path
    pays zero threads.  Note the host fallback gathers via np.asarray,
    which is addressable for single-host meshes; the multi-host recovery
    seam is the validator's guarded call, which still holds the
    process-local host inputs."""
    if mesh is None or len(mesh.devices.flat) <= 1:
        return fit_fn()
    from ..parallel import resilience

    return resilience.guarded_collective(
        label, fit_fn, shrink_fn=host_fit_fn
    )


def _batched_diag(v):
    """[B, d] -> [B, d, d] with v on the diagonals."""
    d = v.shape[-1]
    return v[:, :, None] * jnp.eye(d, dtype=v.dtype)


def newton_fixed_point(step, init, length: int):
    """Run ``carry = step(carry)`` until the carry reaches a BITWISE fixed
    point, or ``length`` iterations - whichever comes first.

    The output is IDENTICAL to ``lax.scan`` of the same step for
    ``length`` iterations: every Newton step here is a deterministic pure
    function of its carry, so once ``step(c) == c`` bit-for-bit, every
    further iteration reproduces ``c`` exactly and running them is pure
    waste.  (The guarded steps make this reachable: ``guarded_step``
    zeroes the beta delta once the gradient is at f32 noise, and the
    intercept update ``b0 - g0/h0`` stops changing ``b0`` once ``g0/h0``
    falls below half a ULP of ``b0``.)  A NaN anywhere in the carry can
    never spuriously terminate the loop (NaN != NaN), so a diverging fit
    runs the full budget exactly like the scan would.

    This is the fused-training-program fit loop (local/fused_train.py,
    ISSUE 15): the whole-fit ``while_loop`` is only expressible when fit
    -> score -> metrics compile as ONE program; the kernel-at-a-time
    dispatch keeps its fixed-length scan as the bit-identical baseline.
    """

    def body(state):
        carry, i, _ = state
        new = step(carry)
        done = jnp.bool_(True)
        for old_leaf, new_leaf in zip(
            jax.tree_util.tree_leaves(carry),
            jax.tree_util.tree_leaves(new),
        ):
            done = done & jnp.all(old_leaf == new_leaf)
        return new, i + jnp.int32(1), done

    def cond(state):
        _, i, done = state
        return (~done) & (i < length)

    carry, _i, _done = jax.lax.while_loop(
        cond, body, (init, jnp.int32(0), jnp.bool_(False))
    )
    return carry


def run_newton(step, init, length: int, fixed_point: bool = False):
    """The one point of truth for the Newton iteration loop: the scan
    form (kernel-at-a-time dispatch, exactly the pre-fused graph) or the
    bitwise fixed-point while loop (fused training programs).  Both
    produce identical carries; only the wasted tail iterations differ."""
    if fixed_point:
        return newton_fixed_point(step, init, length)
    carry, _ = jax.lax.scan(
        lambda c, _: (step(c), None), init, None, length=length
    )
    return carry


_psolve = jax.vmap(partial(jax.scipy.linalg.solve, assume_a="pos"))


def pd_jitter(s_curv, dim: int, hess_bf16: bool, base: float = 1e-9):
    """PD-safety ridge for the Newton kernels' f32/bf16 Cholesky solves,
    the ONE point of truth for the magic constants (retuned twice already;
    six kernels share it).  ``s_curv`` = trace(H)/dim, the mean curvature:
    the ridge must be RELATIVE to it, must grow with the matrix dimension
    (f32 Cholesky rounding ~eps*dim*||H|| - an absolute 1e-9 froze a
    551-wide softmax refit at zero), and bf16-quantized Grams add ~0.4%
    relative error needing the larger slack."""
    return (
        base
        + (1e-6 + 1.2e-7 * dim) * s_curv
        + (1e-3 * s_curv if hess_bf16 else 0.0)
    )


def guarded_step(delta, g, axis=None):
    """A converged fit takes a ZERO step, and a non-finite solve must not
    poison the scan carry (the silent alternative - freezing at zero - is
    exactly what the relative ridge prevents; this guard is the backstop).
    ``axis``: reduction axis of |g| for batched kernels (None = scalar)."""
    import jax.numpy as _jnp

    if axis is None:
        ok = _jnp.max(_jnp.abs(g)) > 1e-7
    else:
        ok = (_jnp.max(_jnp.abs(g), axis=axis) > 1e-7)[:, None]
    return _jnp.where(ok & _jnp.isfinite(delta), delta, 0.0)


def lr_fit_batched_packed_core(
    X, y, W, regs, ens, iters: int, hess_bf16: bool, mesh=None,
    fixed_point: bool = False,
):
    """Explicitly-batched weighted logistic IRLS: X [n, d], y [n],
    W [B, n] per-replica sample weights, regs/ens [B].  Same per-row math
    as logistic_regression._lr_fit_kernel under vmap; the Gram is packed
    (shard_map over ``mesh`` when the caller's arrays are mesh-sharded).
    Returns (beta [B, d] raw-scale, intercept [B]).

    Un-jitted core so the fused training program (local/fused_train.py)
    can trace it INSIDE one fit->score->metrics jit; dtypes are pinned to
    ``X.dtype`` so tracing under an enable_x64 window emits exactly the
    f32 graph the standalone jit emits (``fixed_point=True`` swaps the
    fixed-length scan for the bit-identical early-exit while loop)."""
    n, d = X.shape
    B = W.shape[0]
    wsum = W.sum(axis=1)  # [B]
    # global pre-centering + inactive-column exclusion (per replica):
    # same f32 conditioning fix as the unbatched kernels - the shared
    # matrix is centered ONCE, so replicas still read one array
    m0 = X.mean(axis=0)
    X = X - m0
    mu = (W @ X) / wsum[:, None]  # [B, d]
    msq = (W @ (X * X)) / wsum[:, None]
    var = msq - mu**2
    active = (var > 1e-6 * msq + 1e-30).astype(X.dtype)  # [B, d]
    sd = jnp.where(
        active > 0, jnp.sqrt(jnp.maximum(var, 1e-12)), 1.0
    )
    lam_l2 = regs * (1.0 - ens)
    lam_l1 = regs * ens
    eps = 1e-8
    Xh = X.astype(jnp.bfloat16) if hess_bf16 else X
    Wn = W.T  # [n, B]
    eye = jnp.eye(d, dtype=X.dtype)

    def step(carry):
        beta, b0 = carry  # [B, d], [B]
        gamma = beta / sd  # [B, d]
        z = X @ gamma.T + (b0 - (mu * gamma).sum(axis=1))[None, :]  # [n, B]
        p = jax.nn.sigmoid(z)
        wt = Wn * p * (1.0 - p) + eps  # [n, B]
        resid = Wn * (p - y[:, None])  # [n, B]
        l1_diag = lam_l1[:, None] / (jnp.abs(beta) + 1e-3)  # [B, d]
        Xr = X.T @ resid  # [d, B]
        sr = resid.sum(axis=0)  # [B]
        g = (
            (Xr.T - mu * sr[:, None]) / sd / wsum[:, None]
            + (lam_l2[:, None] + l1_diag) * beta
        ) * active
        XtWX = packed_weighted_gram(
            Xh, wt.astype(Xh.dtype), mesh
        )  # [B, d, d] f32
        a = (X.T @ wt).T  # [B, d]
        s = wt.sum(axis=0)  # [B]
        Hs = (
            XtWX
            - mu[:, :, None] * a[:, None, :]
            - a[:, :, None] * mu[:, None, :]
            + s[:, None, None] * (mu[:, :, None] * mu[:, None, :])
        ) / (sd[:, :, None] * sd[:, None, :]) / wsum[:, None, None]
        Hs = Hs * (active[:, :, None] * active[:, None, :])
        jitter = pd_jitter(
            jnp.trace(Hs, axis1=1, axis2=2) / d, d, hess_bf16
        )
        H = (
            Hs
            + _batched_diag(
                lam_l2[:, None] + l1_diag + (1.0 - active).astype(X.dtype)
            )
            + jitter[:, None, None] * eye
        )
        g0 = sr / wsum
        h0 = s / wsum
        delta = guarded_step(_psolve(H, g), g, axis=1)
        return beta - delta, b0 - g0 / h0

    beta_s, b0 = run_newton(
        step, (jnp.zeros((B, d), X.dtype), jnp.zeros((B,), X.dtype)),
        iters, fixed_point,
    )
    beta = beta_s / sd
    intercept = b0 - ((mu + m0[None, :]) * beta).sum(axis=1)
    return beta, intercept


@partial(jax.jit, static_argnames=("iters", "hess_bf16", "mesh"))
def lr_fit_batched_packed(
    X, y, W, regs, ens, iters: int, hess_bf16: bool, mesh=None
):
    """Jitted kernel-at-a-time wrapper over the core (the pre-fused
    dispatch; reference semantics documented on the core)."""
    return lr_fit_batched_packed_core(
        X, y, W, regs, ens, iters, hess_bf16, mesh
    )


def svc_fit_batched_packed_core(
    X, y, W, regs, iters: int, hess_bf16: bool, mesh=None,
    fixed_point: bool = False,
):
    """Explicitly-batched squared-hinge Newton (linear_svc._svc_fit_kernel
    under vmap, Gram packed).  Returns (beta [B, d], intercept [B]).
    Un-jitted, dtype-pinned core (see lr_fit_batched_packed_core)."""
    n, d = X.shape
    B = W.shape[0]
    ypm = 2.0 * y - 1.0
    wsum = jnp.maximum(W.sum(axis=1), 1e-12)  # [B]
    # global pre-centering + exclusion (see lr_fit_batched_packed)
    m0 = X.mean(axis=0)
    X = X - m0
    mu = (W @ X) / wsum[:, None]
    msq = (W @ (X * X)) / wsum[:, None]
    var = msq - mu**2
    active = (var > 1e-6 * msq + 1e-30).astype(X.dtype)
    sd = jnp.where(active > 0, jnp.sqrt(jnp.maximum(var, 1e-12)), 1.0)
    Xh = X.astype(jnp.bfloat16) if hess_bf16 else X
    Wn = W.T  # [n, B]
    eye = jnp.eye(d, dtype=X.dtype)

    def step(carry):
        beta, b0 = carry
        gamma = beta / sd
        margin = ypm[:, None] * (
            X @ gamma.T + (b0 - (mu * gamma).sum(axis=1))[None, :]
        )  # [n, B]
        act_rows = (margin < 1.0).astype(X.dtype) * Wn  # [n, B]
        r = act_rows * (margin - 1.0) * ypm[:, None]
        sr = r.sum(axis=0)  # [B]
        g = (
            ((X.T @ r).T - mu * sr[:, None]) / sd / wsum[:, None]
            + (2.0 * regs[:, None]) * beta
        ) * active
        XtAX = packed_weighted_gram(Xh, act_rows.astype(Xh.dtype), mesh)
        a = (X.T @ act_rows).T  # [B, d]
        s = act_rows.sum(axis=0)
        Hs = (
            XtAX
            - mu[:, :, None] * a[:, None, :]
            - a[:, :, None] * mu[:, None, :]
            + s[:, None, None] * (mu[:, :, None] * mu[:, None, :])
        ) / (sd[:, :, None] * sd[:, None, :]) / wsum[:, None, None]
        Hs = Hs * (active[:, :, None] * active[:, None, :])
        jitter = pd_jitter(
            jnp.trace(Hs, axis1=1, axis2=2) / d, d, hess_bf16, base=1e-8
        )[:, None, None] * eye
        H = (
            Hs
            + _batched_diag(
                jnp.broadcast_to(2.0 * regs[:, None], (B, d))
                + (1.0 - active).astype(X.dtype)
            )
            + jitter
        )
        g0 = sr / wsum
        h0 = s / wsum + 1e-8
        delta = guarded_step(_psolve(H, g), g, axis=1)
        return beta - delta, b0 - g0 / h0

    beta_s, b0 = run_newton(
        step, (jnp.zeros((B, d), X.dtype), jnp.zeros((B,), X.dtype)),
        iters, fixed_point,
    )
    beta = beta_s / sd
    return beta, b0 - ((mu + m0[None, :]) * beta).sum(axis=1)


@partial(jax.jit, static_argnames=("iters", "hess_bf16", "mesh"))
def svc_fit_batched_packed(
    X, y, W, regs, iters: int, hess_bf16: bool, mesh=None
):
    """Jitted kernel-at-a-time wrapper over the core (the pre-fused
    dispatch; reference semantics documented on the core)."""
    return svc_fit_batched_packed_core(X, y, W, regs, iters, hess_bf16, mesh)


def linreg_fit_batched_packed_core(
    X, y, W, regs, ens, l1_iters: int = 8, mesh=None,
    fixed_point: bool = False,
):
    """Explicitly-batched weighted ridge / elastic-net (normal equations).
    The Gram weights are the FIXED fold masks, so the packed Gram runs
    ONCE - the l1 reweighting scan is [B, d, d] solves only.  The Gram
    stays f32: unlike the Newton kernels it defines the answer, not just
    the step direction.  Returns (beta [B, d], intercept [B]).
    Un-jitted, dtype-pinned core (see lr_fit_batched_packed_core)."""
    n, d = X.shape
    B = W.shape[0]
    wsum = W.sum(axis=1)
    # global pre-centering + exclusion (see lr_fit_batched_packed)
    m0 = X.mean(axis=0)
    X = X - m0
    mu = (W @ X) / wsum[:, None]
    msq = (W @ (X * X)) / wsum[:, None]
    var = msq - mu**2
    active = (var > 1e-6 * msq + 1e-30).astype(X.dtype)
    sd = jnp.where(active > 0, jnp.sqrt(jnp.maximum(var, 1e-12)), 1.0)
    ybar = (W @ y) / wsum
    lam_l2 = regs * (1.0 - ens)
    lam_l1 = regs * ens
    XtWX = packed_weighted_gram(X, W.T, mesh)  # [B, d, d] f32
    a = W @ X  # [B, d]
    G = (
        XtWX
        - mu[:, :, None] * a[:, None, :]
        - a[:, :, None] * mu[:, None, :]
        + wsum[:, None, None] * (mu[:, :, None] * mu[:, None, :])
    ) / (sd[:, :, None] * sd[:, None, :]) / wsum[:, None, None]
    G = G * (active[:, :, None] * active[:, None, :])
    r = W * (y[None, :] - ybar[:, None])  # [B, n]
    c = (
        ((X.T @ r.T).T - mu * r.sum(axis=1)[:, None]) / sd / wsum[:, None]
    ) * active

    # G is fixed across l1 steps, so the dimension-aware ridge prices once
    ridge = pd_jitter(
        jnp.trace(G, axis1=1, axis2=2) / d, d, hess_bf16=False
    )[:, None]

    def step(beta):
        l1_diag = lam_l1[:, None] / (jnp.abs(beta) + 1e-3)
        H = G + _batched_diag(
            lam_l2[:, None] + l1_diag + ridge + (1.0 - active).astype(X.dtype)
        )
        new = _psolve(H, c)
        return jnp.where(jnp.isfinite(new), new, beta)

    beta_s = run_newton(
        step, jnp.zeros((B, d), X.dtype), l1_iters, fixed_point
    )
    beta = beta_s / sd
    intercept = ybar - ((mu + m0[None, :]) * beta).sum(axis=1)
    return beta, intercept


@partial(jax.jit, static_argnames=("l1_iters", "mesh"))
def linreg_fit_batched_packed(X, y, W, regs, ens, l1_iters: int = 8, mesh=None):
    """Jitted kernel-at-a-time wrapper over the core (the pre-fused
    dispatch; reference semantics documented on the core)."""
    return linreg_fit_batched_packed_core(X, y, W, regs, ens, l1_iters, mesh)
