"""Logistic regression trained by jitted, vmappable IRLS/Newton.

Counterpart of OpLogisticRegression (reference: core/.../impl/
classification/OpLogisticRegression.scala:43-75, training done inside Spark
MLlib's LBFGS/OWL-QN).  TPU-first design:

* the whole fit is ONE jitted computation over the dense [n, d] design
  matrix: Newton steps with an [d, d] Cholesky solve - d is small after
  vectorization (hashing caps it), n is the big axis, so each step is a
  couple of MXU matmuls + a psum-able reduction;
* sample weights are first-class: a CV fold or a rebalanced split is a
  weight vector, so fold x hyperparam fan-out = ``vmap`` over (weights,
  lambda) with NO data movement;
* features are standardized inside the kernel (Spark standardization=true
  semantics) and coefficients folded back to raw scale;
* elastic-net L1 is handled with iterated reweighted approximation.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import PredictorEstimator


def _hessian_bf16() -> bool:
    """bf16 Hessian Gram on TPU (MXU rate), f32 elsewhere.  Trace-time
    decision; TX_LR_HESSIAN_BF16=0/1 overrides."""
    import os

    override = os.environ.get("TX_LR_HESSIAN_BF16")
    if override is not None:
        return override.strip().lower() not in ("0", "false", "")
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def lr_newton_core(
    X: jnp.ndarray,
    y: jnp.ndarray,
    w: jnp.ndarray,
    reg: jnp.ndarray,
    elastic_net: jnp.ndarray,
    iters: int = 25,
    fixed_point: bool = False,
):
    """Weighted L2(+approx L1) logistic regression via Newton/IRLS.

    X: [n, d] WITHOUT intercept column; y: [n] in {0,1}; w: [n] sample
    weights; reg: scalar regParam; elastic_net: scalar alpha in [0,1].
    Returns (beta [d], intercept scalar) on the raw feature scale.

    Un-jitted core: ``_lr_fit_kernel`` wraps it for the kernel-at-a-time
    dispatch; the fused training program (local/fused_train.py) traces it
    inside ONE fit->score->metrics jit.  Dtypes are pinned to ``X.dtype``
    so tracing under an enable_x64 window emits exactly the f32 graph the
    standalone jit emits; ``fixed_point=True`` swaps the fixed-length
    Newton scan for the bitwise-fixed-point early-exit loop
    (packed_newton.run_newton - output identical by construction).
    """
    n, d = X.shape
    wsum = w.sum()
    # GLOBAL pre-centering: the folded-standardization identities below
    # compute centered moments by subtracting outer products, which
    # catastrophically cancels in f32 when |mean| >> std (a softmax-score
    # map NaN'd the Cholesky: noise ~eps*mu^2/sd^2 reached the signal's
    # order).  Centering by the unweighted global mean ONCE keeps every
    # replica reading a single shared matrix (the design constraint) while
    # making the per-replica means - and their cancellations - O(sd).
    m0 = X.mean(axis=0)
    X = X - m0
    mu = (w @ X) / wsum
    msq = (w @ (X * X)) / wsum
    var = msq - mu**2
    # (near-)constant-under-w columns are EXCLUDED like Spark's std==0
    # handling (coefficient pinned to 0)
    active = var > 1e-6 * msq + 1e-30
    sd = jnp.where(active, jnp.sqrt(jnp.maximum(var, 1e-12)), 1.0)
    # Standardization is folded into the algebra instead of materializing a
    # standardized copy of X: under vmap over (folds x grid) weight vectors a
    # per-replica Xs would be a [B, n, d] temporary - the whole design
    # matrix duplicated B times.  With the identities
    #   Xs = (X - mu) D^{-1},  D = diag(sd)
    #   Xs^T r = D^{-1} (X^T r - mu sum(r))
    #   Xs^T W Xs = D^{-1} (X^T W X - mu a^T - a mu^T + s mu mu^T) D^{-1},
    #     a = X^T W 1, s = 1^T W 1
    # every step reads the SHARED X (elementwise weights fuse into the
    # matmuls), so replicas add only O(d^2) state.
    lam_l2 = reg * (1.0 - elastic_net)
    lam_l1 = reg * elastic_net
    eps = 1e-8
    # the Hessian Gram X^T W X is the FLOPs hot spot (n d^2 per step per
    # replica) and only steers the Newton DIRECTION - the converged fixed
    # point is where the f32 gradient vanishes, so approximate curvature
    # changes the path, not the answer.  On TPU the MXU runs bf16 matmuls
    # ~4x the f32 rate: compute the Gram from a bf16 view of X with f32
    # accumulation there, keep every gradient quantity f32.
    hess_bf16 = _hessian_bf16()
    Xh = X.astype(jnp.bfloat16) if hess_bf16 else X

    def step(carry):
        beta, b0 = carry  # beta in standardized space
        gamma = beta / sd
        z = X @ gamma + (b0 - mu @ gamma)
        p = jax.nn.sigmoid(z)
        wt = w * p * (1.0 - p) + eps
        resid = w * (p - y)
        l1_diag = lam_l1 / (jnp.abs(beta) + 1e-3)
        Xr = X.T @ resid
        sr = resid.sum()
        g = ((Xr - mu * sr) / sd / wsum + (lam_l2 + l1_diag) * beta) * active
        if hess_bf16:
            XtWX = jnp.matmul(
                Xh.T, Xh * wt.astype(jnp.bfloat16)[:, None],
                preferred_element_type=jnp.float32,
            )
        else:
            XtWX = X.T @ (X * wt[:, None])
        a = wt @ X
        s = wt.sum()
        Hs = (
            XtWX - jnp.outer(mu, a) - jnp.outer(a, mu) + s * jnp.outer(mu, mu)
        ) / jnp.outer(sd, sd) / wsum
        # curvature-relative, dimension-aware PD jitter + guarded step:
        # see packed_newton.pd_jitter/guarded_step (shared by all six
        # Newton kernels; the jitter steers only the step, the f32
        # gradient still defines the fixed point)
        from .packed_newton import guarded_step, pd_jitter

        jitter = pd_jitter(jnp.trace(Hs) / d, d, hess_bf16)
        # excluded columns: identity row/col so the solve leaves them 0
        amask = jnp.outer(active, active)
        Hs_m = Hs * amask
        H = (
            Hs_m + jnp.diag(lam_l2 + l1_diag)
            + jitter * jnp.eye(d, dtype=X.dtype)
            + jnp.diag((1.0 - active).astype(X.dtype))
        )
        g0 = sr / wsum
        h0 = s / wsum
        delta = guarded_step(
            jax.scipy.linalg.solve(H, g, assume_a="pos"), g
        )
        return beta - delta, b0 - g0 / h0

    from .packed_newton import run_newton

    beta_s, b0 = run_newton(
        step, (jnp.zeros((d,), X.dtype), jnp.zeros((), X.dtype)),
        iters, fixed_point,
    )
    beta = beta_s / sd
    intercept = b0 - ((mu + m0) * beta).sum()  # un-center the intercept
    return beta, intercept


@partial(jax.jit, static_argnames=("iters",))
def _lr_fit_kernel(X, y, w, reg, elastic_net, iters: int = 25):
    """Jitted kernel-at-a-time wrapper over :func:`lr_newton_core`."""
    return lr_newton_core(X, y, w, reg, elastic_net, iters)


def lr_fit_batched_core(X, y, W, regs, ens, iters: int = 25,
                        fixed_point: bool = False):
    """The vmapped fold x grid batch over the shared design matrix: ONE
    computation = the whole CV fan-out (un-jitted so fused training
    programs can trace it; ``_lr_fit_batched`` is the dispatch wrapper)."""
    return jax.vmap(
        lambda w, reg, en: lr_newton_core(X, y, w, reg, en, iters,
                                          fixed_point)
    )(W, regs, ens)


@partial(jax.jit, static_argnames=("iters",))
def _lr_fit_batched(X, y, W, regs, ens, iters: int = 25):
    return lr_fit_batched_core(X, y, W, regs, ens, iters)


@partial(jax.jit, static_argnames=("iters",))
def _softmax_fit_folds(X, Yoh, W, reg, elastic_net, iters: int = 25):
    """Fold-vmapped softmax fits: W [k, n] per-fold sample weights over
    one shared (X, Yoh).  MEMORY NOTE: unlike the binary kernel's folded
    standardization, _softmax_fit_kernel materializes a standardized
    [n, d] copy per replica, so the fold axis multiplies that copy (and
    the [n, K, K] curvature tensor) k times - fit_arrays_folds gates on
    an element budget and falls back to a per-fold host loop past it."""
    return jax.vmap(
        lambda w: _softmax_fit_kernel(X, Yoh, w, reg, elastic_net, iters)
    )(W)


@partial(jax.jit, static_argnames=("iters",))
def _softmax_fit_kernel(X, Yoh, w, reg, elastic_net, iters: int = 25):
    """Weighted multinomial (softmax) logistic regression via full Newton.

    X: [n, d] WITHOUT intercept column; Yoh: [n, K] one-hot labels; w: [n]
    sample weights.  Matches the reference's family="multinomial" semantics
    (OpLogisticRegression.scala:110-116 -> MLlib softmax under LBFGS/OWLQN):
    the model IS jointly normalized - probabilities are a softmax over the
    K linear scores by construction, not an OVR renormalization.

    TPU mapping: the [Kd, Kd] Hessian's K^2 class-pair blocks
    X^T diag(w p_a (d_ab - p_b)) X are ONE packed matmul - the class-pair
    axis rides the matmul N dimension via packed_newton._gram_2d, the same
    MXU-packing move the CV fan-out uses (B there = K^2 here).  K*d stays
    small (d capped by hashing, K by cardinality guards), so the Newton
    solve is a single [Kd+K]^2 Cholesky.

    Same conditioning contract as _lr_fit_kernel: global pre-centering,
    weighted standardization, near-constant column exclusion, approximate
    L1 via iterated reweighting.  Unlike the binary kernel the
    standardization is materialized, not folded - cheap for a single fit,
    but under the fold vmap (_softmax_fit_folds) the copy multiplies per
    replica, hence the element budget in fit_arrays_folds.
    Returns (betas [K, d] raw scale, intercepts [K]).
    """
    n, d = X.shape
    K = Yoh.shape[1]
    wsum = w.sum()
    m0 = X.mean(axis=0)
    X = X - m0
    mu = (w @ X) / wsum
    msq = (w @ (X * X)) / wsum
    var = msq - mu**2
    active = var > 1e-6 * msq + 1e-30
    sd = jnp.where(active, jnp.sqrt(jnp.maximum(var, 1e-12)), 1.0)
    Xs = (X - mu) / sd * active
    lam_l2 = reg * (1.0 - elastic_net)
    lam_l1 = reg * elastic_net
    hess_bf16 = _hessian_bf16()
    Xh = Xs.astype(jnp.bfloat16) if hess_bf16 else Xs
    eyeK = jnp.eye(K)

    from .packed_newton import _gram_2d

    def step(carry, _):
        B, b0 = carry  # [K, d] standardized-space, [K]
        z = Xs @ B.T + b0  # [n, K]
        Pm = jax.nn.softmax(z, axis=1)
        R = w[:, None] * (Pm - Yoh)  # [n, K]
        l1d = lam_l1 / (jnp.abs(B) + 1e-3)  # [K, d]
        gB = (R.T @ Xs) / wsum + (lam_l2 + l1d) * B  # [K, d]
        gB = gB * active[None, :]
        g0 = R.sum(axis=0) / wsum  # [K]
        # class-pair curvature weights: M[n, a, b] = w p_a (d_ab - p_b);
        # the eps diagonal floor mirrors the binary kernel's
        # wt = w p(1-p) + eps - on separable data with reg=0 the MLE
        # diverges and saturated probabilities zero the curvature, so the
        # floor keeps H bounded below and the iterates finite
        M = w[:, None, None] * Pm[:, :, None] * (
            eyeK[None, :, :] - Pm[:, None, :]
        ) + 1e-8 * eyeK[None, :, :]
        M2 = M.reshape(n, K * K)
        G = _gram_2d(Xh, M2.astype(Xh.dtype))  # [d, K*K*d] f32
        Hbb = (
            G.reshape(d, K, K, d).transpose(1, 0, 2, 3).reshape(K * d, K * d)
            / wsum
        )
        HbB = (M2.T @ Xs).reshape(K, K, d) / wsum  # [a, b, j]
        Hb0 = M.sum(axis=0) / wsum  # [K, K]
        # assemble [[Hbb, HbB^T], [HbB, Hb0]] over (K*d + K) params
        top = jnp.concatenate(
            [Hbb, HbB.transpose(1, 2, 0).reshape(K * d, K)], axis=1
        )
        bot = jnp.concatenate([HbB.reshape(K, K * d), Hb0], axis=1)
        H = jnp.concatenate([top, bot], axis=0)
        # The softmax shift invariance (adding any affine score c(x) to
        # ALL classes) makes H exactly singular along K flat directions
        # whose gradient is also exactly zero - so a ridge resolves them
        # without moving the Newton fixed point (g=0 defines the answer,
        # the ridge only bounds the step).  The ridge must be RELATIVE to
        # the curvature scale: an absolute 1e-8 leaves the f32 Cholesky a
        # ~5e7 condition number (> 1/eps_f32) and it NaNs - found on the
        # Iris design matrix.  It must ALSO grow with the matrix
        # dimension: f32 Cholesky rounding error scales ~eps*dim*||H||,
        # and at K*d+K ~ 1.6k (a 550-wide transmogrified matrix, K=3) a
        # 1e-6*s ridge sat BELOW the rounding noise - the very first
        # solve NaN'd and the isfinite guard silently froze the fit at
        # zero (found by the workflow fuzz).  pd_jitter is the shared
        # point of truth for the constants.
        from .packed_newton import pd_jitter

        tr = jnp.trace(H)  # pure curvature scale, before any diag terms
        dim = K * d + K
        s = tr / dim
        jitter = pd_jitter(s, dim, hess_bf16)
        # the excluded-column identity diag is SCALED to the curvature
        # (not a flat 1.0): on separable data with reg=0 the active-block
        # curvature decays exponentially as probabilities saturate, and a
        # 1.0 diag against ~1e-7 curvature sends the f32 Cholesky past
        # its conditioning limit (found on a fully-separated 3-class fit)
        diagB = (
            (lam_l2 + l1d) * active[None, :]
            + (s + 1e-9) * (1.0 - active)[None, :]
        ).reshape(K * d)
        H = H + jnp.diag(jnp.concatenate([diagB, jnp.zeros((K,))]))
        H = H + jitter * jnp.eye(K * d + K)
        g = jnp.concatenate([gB.reshape(K * d), g0])
        from .packed_newton import guarded_step

        # converged fits take a ZERO step: once |g| is at f32 noise the
        # remaining iterations only exercise the collapsed-curvature
        # solve, whose output (even NaN) must not touch the answer
        delta = guarded_step(
            jax.scipy.linalg.solve(H, g, assume_a="pos"), g
        )
        return (
            B - delta[: K * d].reshape(K, d),
            b0 - delta[K * d:],
        ), None

    (B_s, b0), _ = jax.lax.scan(
        step, (jnp.zeros((K, d)), jnp.zeros((K,))), None, length=iters
    )
    betas = B_s * active[None, :] / sd[None, :]
    intercepts = b0 - betas @ (mu + m0)
    return betas, intercepts


@jax.jit
def _lr_predict_kernel(X: jnp.ndarray, beta: jnp.ndarray, intercept: jnp.ndarray):
    z = X @ beta + intercept
    p1 = jax.nn.sigmoid(z)
    prob = jnp.stack([1.0 - p1, p1], axis=1)
    raw = jnp.stack([-z, z], axis=1)
    pred = (p1 > 0.5).astype(z.dtype)
    return pred, raw, prob


def _one_hot(y: np.ndarray, classes: np.ndarray) -> np.ndarray:
    idx = np.searchsorted(classes, y)
    Yoh = np.zeros((len(y), len(classes)), np.float32)
    Yoh[np.arange(len(y)), idx] = 1.0
    return Yoh


def _multinomial_params(betas, b0s, classes: np.ndarray) -> dict:
    """ONE param-dict schema for every multinomial fit path (single,
    fold-vmapped) so CV-fitted fold params can never drift from
    final-fit params."""
    return {
        "betas": np.asarray(betas, np.float64),
        "intercepts": np.asarray(b0s, np.float64),
        "classes": classes.astype(np.float64),
        "family": "multinomial",
    }


class OpLogisticRegression(PredictorEstimator):
    """(reference: OpLogisticRegression.scala; default grid in
    DefaultSelectorParams.scala:36-61 - regParam {0.001,0.01,0.1,0.2},
    elasticNet {0.1,0.5})"""

    #: fused serving seam: predict_arrays_np is pure numpy over host betas
    lowerable = True

    model_type = "OpLogisticRegression"

    def __init__(
        self,
        reg_param: float = 0.0,
        elastic_net_param: float = 0.0,
        max_iter: int = 25,
        fit_intercept: bool = True,
        family: str = "auto",
        **kw,
    ) -> None:
        super().__init__(**kw)
        self.params.setdefault("reg_param", reg_param)
        self.params.setdefault("elastic_net_param", elastic_net_param)
        self.params.setdefault("max_iter", max_iter)
        self.params.setdefault("fit_intercept", fit_intercept)
        # reference semantics (OpLogisticRegression.scala:110-116): 'auto'
        # -> binomial on <=2 classes, multinomial (softmax) otherwise.
        # 'ovr' keeps round-4's one-vs-rest route as an explicit option.
        fam = str(family).lower()
        if fam not in ("auto", "binomial", "multinomial", "ovr"):
            raise ValueError(f"unknown logistic family: {family!r}")
        self.params.setdefault("family", fam)

    def _multiclass_family(self, K: int, d: int) -> str:
        fam = str(self.params.get("family", "auto")).lower()
        if fam == "ovr":
            return "ovr"
        if fam == "binomial":
            # reference MLlib contract: binomial refuses >2 outcome
            # classes rather than silently fitting something else
            raise ValueError(
                f"family='binomial' supports at most 2 outcome classes; "
                f"the label column has {K}"
            )
        if fam == "multinomial":
            return "multinomial"  # explicit request is always honored
        if fam == "auto":
            # the softmax Newton solves a [K(d+1)]^2 system; past ~2048
            # params the OVR route's K independent [d, d] solves win
            return "ovr" if K * (d + 1) > 2048 else "multinomial"
        raise ValueError(f"unknown logistic family: {fam!r}")

    def fit_arrays(self, X, y, w=None):
        n = len(y)
        w = np.ones(n) if w is None else w
        classes = np.unique(np.asarray(y))
        if len(classes) > 2:
            K = len(classes)
            d = np.shape(X)[1]
            if self._multiclass_family(K, d) == "multinomial":
                betas, b0s = _softmax_fit_kernel(
                    jnp.asarray(X, jnp.float32),
                    jnp.asarray(_one_hot(np.asarray(y), classes)),
                    jnp.asarray(w, jnp.float32),
                    jnp.asarray(float(self.params["reg_param"])),
                    jnp.asarray(float(self.params["elastic_net_param"])),
                    iters=int(self.params["max_iter"]),
                )
                return _multinomial_params(betas, b0s, classes)
            # one-vs-rest over the SAME binary Newton kernel (kept as the
            # family='ovr' option + the large-K*d fallback).  K is small,
            # so a host loop of jitted fits is fine; each fit reuses the
            # same compiled kernel (shapes identical).
            betas, b0s = [], []
            for c in classes:
                beta, b0 = _lr_fit_kernel(
                    jnp.asarray(X),
                    jnp.asarray((np.asarray(y) == c).astype(np.float64)),
                    jnp.asarray(w),
                    jnp.asarray(float(self.params["reg_param"])),
                    jnp.asarray(float(self.params["elastic_net_param"])),
                    iters=int(self.params["max_iter"]),
                )
                betas.append(np.asarray(beta))
                b0s.append(float(b0))
            return {
                "betas": np.stack(betas),
                "intercepts": np.asarray(b0s),
                "classes": classes.astype(np.float64),
                "family": "ovr",
            }
        beta, b0 = _lr_fit_kernel(
            jnp.asarray(X),
            jnp.asarray(y),
            jnp.asarray(w),
            jnp.asarray(float(self.params["reg_param"])),
            jnp.asarray(float(self.params["elastic_net_param"])),
            iters=int(self.params["max_iter"]),
        )
        return {"beta": np.asarray(beta), "intercept": float(b0)}

    def fit_arrays_batched(self, X, y, W, regs, ens):
        """Batched fit: W [B, n] weight masks, regs/ens [B] -> stacked params.
        One computation = the whole CV x grid fan-out.  TPU inputs ride
        the MXU-packed explicit batch (packed_newton.py, the Gram packs
        all replicas into the matmul N dimension); mesh-sharded inputs
        keep packing via the shard_map Gram, with rows on 'data' and
        replicas on 'replica'."""
        from .packed_newton import (
            lr_fit_batched_packed,
            packed_mesh_or_none,
            run_packed_guarded,
            use_packed,
        )

        iters = int(self.params.get("max_iter", 25))
        if use_packed(X, W):
            mesh = packed_mesh_or_none(X, W)

            def _packed_fit(m, Xa, ya, Wa):
                return lr_fit_batched_packed(
                    jnp.asarray(Xa), jnp.asarray(ya), jnp.asarray(Wa),
                    jnp.asarray(regs), jnp.asarray(ens),
                    iters=iters, hess_bf16=_hessian_bf16(), mesh=m,
                )

            beta, b0 = run_packed_guarded(
                "lr.packed_gram",
                lambda: _packed_fit(mesh, X, y, W),
                lambda: _packed_fit(
                    None, np.asarray(X), np.asarray(y), np.asarray(W)),
                mesh,
            )
        else:
            beta, b0 = _lr_fit_batched(
                jnp.asarray(X), jnp.asarray(y), jnp.asarray(W),
                jnp.asarray(regs), jnp.asarray(ens), iters=iters,
            )
        return np.asarray(beta), np.asarray(b0)

    def fused_train_core(self, packed: bool):
        """Traceable (fit, score) pair for the fused training program
        (local/fused_train.py, ISSUE 15): ``fit`` is the SAME batched
        Newton math the kernel-at-a-time dispatch runs (vmap or packed
        route picked by the caller with the same ``use_packed`` rule),
        with the bitwise-fixed-point early exit; ``score`` mirrors
        ``_lr_predict_kernel``'s ranking score (prob of class 1) op for
        op over the FULL design matrix - the caller gathers validation
        rows from the [n] score vector, because per-row dots over the
        parameter X are bit-equal to the per-candidate dispatch while a
        dot over a gathered operand picks a different CPU emitter.
        Binary labels only - the validator's ``_labels_ok`` gate owns
        that."""
        iters = int(self.params.get("max_iter", 25))
        # the Hessian dtype is baked in at TRACE time (vmap route reads
        # it inside the core, packed route here), so it must be part of
        # the program signature: a TX_LR_HESSIAN_BF16 flip mid-process
        # must retrace, not silently reuse the old-precision program
        hess_bf16 = _hessian_bf16()
        if packed:
            from .packed_newton import lr_fit_batched_packed_core

            def fit(X, y, W, regs, ens):
                return lr_fit_batched_packed_core(
                    X, y, W, regs, ens, iters=iters,
                    hess_bf16=hess_bf16, fixed_point=True,
                )
        else:
            def fit(X, y, W, regs, ens):
                return lr_fit_batched_core(
                    X, y, W, regs, ens, iters, fixed_point=True
                )

        def score(X, beta, b0):
            return jax.nn.sigmoid(X @ beta + b0)

        return {"fit": fit, "score": score,
                "sig": ("lr", iters, packed, hess_bf16)}

    def fit_arrays_folds(self, X, y, W):
        """One config, k folds in one vmapped dispatch: W [k, n] per-fold
        sample weights -> list of per-fold param dicts.  The validator's
        fold-batched branch picks this up for MULTICLASS labels (binary
        grids ride the fully-batched fold x grid route instead), so a
        3-class CV runs k softmax Newtons as one computation rather than
        a per-(fold, config) host loop."""
        import os

        reg = float(self.params["reg_param"])
        en = float(self.params["elastic_net_param"])
        iters = int(self.params["max_iter"])
        y_np = np.asarray(y)
        classes = np.unique(y_np)
        n, d = np.shape(X)
        k = np.asarray(W).shape[0]
        if len(classes) > 2 and self._multiclass_family(
            len(classes), d
        ) == "multinomial":
            K = len(classes)
            # the softmax kernel materializes per-replica standardized
            # copies + the [n, K, K] curvature tensor; past this element
            # budget the fold vmap would multiply that by k, so fall
            # back to a per-fold host loop (TX_LR_FOLDS_ELEMS overrides)
            budget = int(os.environ.get("TX_LR_FOLDS_ELEMS", 1 << 27))
            if k * n * (d + K * K) > budget:
                return [
                    self.fit_arrays(X, y, np.asarray(W)[f])
                    for f in range(k)
                ]
            betas, b0s = _softmax_fit_folds(
                jnp.asarray(X, jnp.float32),
                jnp.asarray(_one_hot(y_np, classes)),
                jnp.asarray(W, jnp.float32),
                jnp.asarray(reg), jnp.asarray(en), iters=iters,
            )
            betas, b0s = np.asarray(betas), np.asarray(b0s)
            return [
                _multinomial_params(betas[f], b0s[f], classes)
                for f in range(k)
            ]
        if len(classes) > 2:  # ovr (or the large-K*d fallback): per fold
            return [
                self.fit_arrays(X, y, np.asarray(W)[f]) for f in range(k)
            ]
        # binary: reuse the fully-batched kernel with the config tiled
        # per fold (no separate fold entry point to keep in sync)
        betas, b0s = _lr_fit_batched(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(W),
            jnp.full((k,), reg), jnp.full((k,), en), iters=iters,
        )
        betas, b0s = np.asarray(betas), np.asarray(b0s)
        return [
            {"beta": betas[f], "intercept": float(b0s[f])} for f in range(k)
        ]

    def predict_arrays(self, params: Any, X: np.ndarray):
        if "betas" in params:  # one-vs-rest multiclass
            return self.predict_arrays_np(params, np.asarray(X))
        pred, raw, prob = _lr_predict_kernel(
            jnp.asarray(X), jnp.asarray(params["beta"]),
            jnp.asarray(params["intercept"]),
        )
        return np.asarray(pred), np.asarray(raw), np.asarray(prob)

    def predict_arrays_np(self, params: Any, X: np.ndarray):
        if "betas" in params:
            z = X @ params["betas"].T + params["intercepts"]  # [n, K]
            z = np.clip(z, -500, 500)
            # family='multinomial': softmax IS the model (jointly
            # normalized by construction); family='ovr': softmax over the
            # per-class margins normalizes the independent OvR scores
            e = np.exp(z - z.max(axis=1, keepdims=True))
            prob = e / e.sum(axis=1, keepdims=True)
            pred = params["classes"][np.argmax(prob, axis=1)]
            return pred.astype(np.float64), z, prob
        z = X @ params["beta"] + params["intercept"]
        p1 = 1.0 / (1.0 + np.exp(-np.clip(z, -500, 500)))
        prob = np.stack([1.0 - p1, p1], axis=1)
        raw = np.stack([-z, z], axis=1)
        pred = (p1 > 0.5).astype(np.float64)
        return pred, raw, prob

    def predict_arrays_xla(self, params: Any, X):
        """jax-traceable mirror of ``predict_arrays_np`` for the XLA
        fused backend (local/fused_xla.py); the margin matmul rides
        XLA's dot emitter, so parity vs BLAS is a few-ULP budget, not
        bit-exact (pinned in tests/test_fused_xla.py)."""
        if "betas" in params:
            z = X @ jnp.asarray(params["betas"]).T + jnp.asarray(
                params["intercepts"]
            )
            z = jnp.clip(z, -500, 500)
            e = jnp.exp(z - z.max(axis=1, keepdims=True))
            prob = e / e.sum(axis=1, keepdims=True)
            classes = jnp.asarray(np.asarray(params["classes"],
                                             dtype=np.float64))
            pred = classes[jnp.argmax(prob, axis=1)]
            return pred.astype(jnp.float64), z, prob
        z = X @ jnp.asarray(params["beta"]) + params["intercept"]
        p1 = 1.0 / (1.0 + jnp.exp(-jnp.clip(z, -500, 500)))
        prob = jnp.stack([1.0 - p1, p1], axis=1)
        raw = jnp.stack([-z, z], axis=1)
        pred = (p1 > 0.5).astype(jnp.float64)
        return pred, raw, prob

    def contributions(self, params: Any) -> Optional[np.ndarray]:
        if "betas" in params:
            return np.abs(params["betas"]).mean(axis=0)
        return np.abs(params["beta"])
