"""Logistic regression trained by jitted, vmappable IRLS/Newton.

Counterpart of OpLogisticRegression (reference: core/.../impl/
classification/OpLogisticRegression.scala:43-75, training done inside Spark
MLlib's LBFGS/OWL-QN).  TPU-first design:

* the whole fit is ONE jitted computation over the dense [n, d] design
  matrix: Newton steps with an [d, d] Cholesky solve - d is small after
  vectorization (hashing caps it), n is the big axis, so each step is a
  couple of MXU matmuls + a psum-able reduction;
* sample weights are first-class: a CV fold or a rebalanced split is a
  weight vector, so fold x hyperparam fan-out = ``vmap`` over (weights,
  lambda) with NO data movement;
* features are standardized inside the kernel (Spark standardization=true
  semantics) and coefficients folded back to raw scale;
* elastic-net L1 is handled with iterated reweighted approximation.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import PredictorEstimator


def _hessian_bf16() -> bool:
    """bf16 Hessian Gram on TPU (MXU rate), f32 elsewhere.  Trace-time
    decision; TX_LR_HESSIAN_BF16=0/1 overrides."""
    import os

    override = os.environ.get("TX_LR_HESSIAN_BF16")
    if override is not None:
        return override.strip().lower() not in ("0", "false", "")
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@partial(jax.jit, static_argnames=("iters",))
def _lr_fit_kernel(
    X: jnp.ndarray,
    y: jnp.ndarray,
    w: jnp.ndarray,
    reg: jnp.ndarray,
    elastic_net: jnp.ndarray,
    iters: int = 25,
):
    """Weighted L2(+approx L1) logistic regression via Newton/IRLS.

    X: [n, d] WITHOUT intercept column; y: [n] in {0,1}; w: [n] sample
    weights; reg: scalar regParam; elastic_net: scalar alpha in [0,1].
    Returns (beta [d], intercept scalar) on the raw feature scale.
    """
    n, d = X.shape
    wsum = w.sum()
    # GLOBAL pre-centering: the folded-standardization identities below
    # compute centered moments by subtracting outer products, which
    # catastrophically cancels in f32 when |mean| >> std (a softmax-score
    # map NaN'd the Cholesky: noise ~eps*mu^2/sd^2 reached the signal's
    # order).  Centering by the unweighted global mean ONCE keeps every
    # replica reading a single shared matrix (the design constraint) while
    # making the per-replica means - and their cancellations - O(sd).
    m0 = X.mean(axis=0)
    X = X - m0
    mu = (w @ X) / wsum
    msq = (w @ (X * X)) / wsum
    var = msq - mu**2
    # (near-)constant-under-w columns are EXCLUDED like Spark's std==0
    # handling (coefficient pinned to 0)
    active = var > 1e-6 * msq + 1e-30
    sd = jnp.where(active, jnp.sqrt(jnp.maximum(var, 1e-12)), 1.0)
    # Standardization is folded into the algebra instead of materializing a
    # standardized copy of X: under vmap over (folds x grid) weight vectors a
    # per-replica Xs would be a [B, n, d] temporary - the whole design
    # matrix duplicated B times.  With the identities
    #   Xs = (X - mu) D^{-1},  D = diag(sd)
    #   Xs^T r = D^{-1} (X^T r - mu sum(r))
    #   Xs^T W Xs = D^{-1} (X^T W X - mu a^T - a mu^T + s mu mu^T) D^{-1},
    #     a = X^T W 1, s = 1^T W 1
    # every step reads the SHARED X (elementwise weights fuse into the
    # matmuls), so replicas add only O(d^2) state.
    lam_l2 = reg * (1.0 - elastic_net)
    lam_l1 = reg * elastic_net
    eps = 1e-8
    # the Hessian Gram X^T W X is the FLOPs hot spot (n d^2 per step per
    # replica) and only steers the Newton DIRECTION - the converged fixed
    # point is where the f32 gradient vanishes, so approximate curvature
    # changes the path, not the answer.  On TPU the MXU runs bf16 matmuls
    # ~4x the f32 rate: compute the Gram from a bf16 view of X with f32
    # accumulation there, keep every gradient quantity f32.
    hess_bf16 = _hessian_bf16()
    Xh = X.astype(jnp.bfloat16) if hess_bf16 else X

    def step(carry, _):
        beta, b0 = carry  # beta in standardized space
        gamma = beta / sd
        z = X @ gamma + (b0 - mu @ gamma)
        p = jax.nn.sigmoid(z)
        wt = w * p * (1.0 - p) + eps
        resid = w * (p - y)
        l1_diag = lam_l1 / (jnp.abs(beta) + 1e-3)
        Xr = X.T @ resid
        sr = resid.sum()
        g = ((Xr - mu * sr) / sd / wsum + (lam_l2 + l1_diag) * beta) * active
        if hess_bf16:
            XtWX = jnp.matmul(
                Xh.T, Xh * wt.astype(jnp.bfloat16)[:, None],
                preferred_element_type=jnp.float32,
            )
        else:
            XtWX = X.T @ (X * wt[:, None])
        a = wt @ X
        s = wt.sum()
        Hs = (
            XtWX - jnp.outer(mu, a) - jnp.outer(a, mu) + s * jnp.outer(mu, mu)
        ) / jnp.outer(sd, sd) / wsum
        # bf16 Gram error (~0.4% relative) can push a near-singular H
        # indefinite past the tiny base jitter and NaN the pos-assumed
        # solve; scale the jitter with the curvature magnitude when the
        # quantized Gram is in play (jitter is curvature-only - the f32
        # gradient still defines the fixed point)
        jitter = 1e-9 + (
            1e-3 * jnp.trace(Hs) / d if hess_bf16 else 0.0
        )
        # excluded columns: identity row/col so the solve leaves them 0
        amask = jnp.outer(active, active)
        Hs = Hs * amask
        H = (
            Hs + jnp.diag(lam_l2 + l1_diag) + jitter * jnp.eye(d)
            + jnp.diag(1.0 - active)
        )
        g0 = sr / wsum
        h0 = s / wsum
        delta = jax.scipy.linalg.solve(H, g, assume_a="pos")
        return (beta - delta, b0 - g0 / h0), None

    (beta_s, b0), _ = jax.lax.scan(
        step, (jnp.zeros((d,)), jnp.asarray(0.0)), None, length=iters
    )
    beta = beta_s / sd
    intercept = b0 - ((mu + m0) * beta).sum()  # un-center the intercept
    return beta, intercept


@partial(jax.jit, static_argnames=("iters",))
def _lr_fit_batched(X, y, W, regs, ens, iters: int = 25):
    return jax.vmap(
        lambda w, reg, en: _lr_fit_kernel(X, y, w, reg, en, iters)
    )(W, regs, ens)


@jax.jit
def _lr_predict_kernel(X: jnp.ndarray, beta: jnp.ndarray, intercept: jnp.ndarray):
    z = X @ beta + intercept
    p1 = jax.nn.sigmoid(z)
    prob = jnp.stack([1.0 - p1, p1], axis=1)
    raw = jnp.stack([-z, z], axis=1)
    pred = (p1 > 0.5).astype(z.dtype)
    return pred, raw, prob


class OpLogisticRegression(PredictorEstimator):
    """(reference: OpLogisticRegression.scala; default grid in
    DefaultSelectorParams.scala:36-61 - regParam {0.001,0.01,0.1,0.2},
    elasticNet {0.1,0.5})"""

    model_type = "OpLogisticRegression"

    def __init__(
        self,
        reg_param: float = 0.0,
        elastic_net_param: float = 0.0,
        max_iter: int = 25,
        fit_intercept: bool = True,
        **kw,
    ) -> None:
        super().__init__(**kw)
        self.params.setdefault("reg_param", reg_param)
        self.params.setdefault("elastic_net_param", elastic_net_param)
        self.params.setdefault("max_iter", max_iter)
        self.params.setdefault("fit_intercept", fit_intercept)

    def fit_arrays(self, X, y, w=None):
        n = len(y)
        w = np.ones(n) if w is None else w
        classes = np.unique(np.asarray(y))
        if len(classes) > 2:
            # multiclass: one-vs-rest over the SAME binary Newton kernel
            # (reference OpLogisticRegression is multinomial via MLlib;
            # OvR + softmax normalization is the measured equivalent here
            # - quality pinned by tests/test_models.py multiclass case).
            # K is small, so a host loop of jitted fits is fine; each fit
            # reuses the same compiled kernel (shapes identical).
            betas, b0s = [], []
            for c in classes:
                beta, b0 = _lr_fit_kernel(
                    jnp.asarray(X),
                    jnp.asarray((np.asarray(y) == c).astype(np.float64)),
                    jnp.asarray(w),
                    jnp.asarray(float(self.params["reg_param"])),
                    jnp.asarray(float(self.params["elastic_net_param"])),
                    iters=int(self.params["max_iter"]),
                )
                betas.append(np.asarray(beta))
                b0s.append(float(b0))
            return {
                "betas": np.stack(betas),
                "intercepts": np.asarray(b0s),
                "classes": classes.astype(np.float64),
            }
        beta, b0 = _lr_fit_kernel(
            jnp.asarray(X),
            jnp.asarray(y),
            jnp.asarray(w),
            jnp.asarray(float(self.params["reg_param"])),
            jnp.asarray(float(self.params["elastic_net_param"])),
            iters=int(self.params["max_iter"]),
        )
        return {"beta": np.asarray(beta), "intercept": float(b0)}

    def fit_arrays_batched(self, X, y, W, regs, ens):
        """Batched fit: W [B, n] weight masks, regs/ens [B] -> stacked params.
        One computation = the whole CV x grid fan-out.  TPU inputs ride
        the MXU-packed explicit batch (packed_newton.py, the Gram packs
        all replicas into the matmul N dimension); mesh-sharded inputs
        keep packing via the shard_map Gram, with rows on 'data' and
        replicas on 'replica'."""
        from .packed_newton import (
            lr_fit_batched_packed,
            packed_mesh_or_none,
            use_packed,
        )

        iters = int(self.params.get("max_iter", 25))
        if use_packed(X, W):
            beta, b0 = lr_fit_batched_packed(
                jnp.asarray(X), jnp.asarray(y), jnp.asarray(W),
                jnp.asarray(regs), jnp.asarray(ens),
                iters=iters, hess_bf16=_hessian_bf16(),
                mesh=packed_mesh_or_none(X, W),
            )
        else:
            beta, b0 = _lr_fit_batched(
                jnp.asarray(X), jnp.asarray(y), jnp.asarray(W),
                jnp.asarray(regs), jnp.asarray(ens), iters=iters,
            )
        return np.asarray(beta), np.asarray(b0)

    def predict_arrays(self, params: Any, X: np.ndarray):
        if "betas" in params:  # one-vs-rest multiclass
            return self.predict_arrays_np(params, np.asarray(X))
        pred, raw, prob = _lr_predict_kernel(
            jnp.asarray(X), jnp.asarray(params["beta"]),
            jnp.asarray(params["intercept"]),
        )
        return np.asarray(pred), np.asarray(raw), np.asarray(prob)

    def predict_arrays_np(self, params: Any, X: np.ndarray):
        if "betas" in params:
            z = X @ params["betas"].T + params["intercepts"]  # [n, K]
            z = np.clip(z, -500, 500)
            # softmax over the per-class margins normalizes the OvR scores
            e = np.exp(z - z.max(axis=1, keepdims=True))
            prob = e / e.sum(axis=1, keepdims=True)
            pred = params["classes"][np.argmax(prob, axis=1)]
            return pred.astype(np.float64), z, prob
        z = X @ params["beta"] + params["intercept"]
        p1 = 1.0 / (1.0 + np.exp(-np.clip(z, -500, 500)))
        prob = np.stack([1.0 - p1, p1], axis=1)
        raw = np.stack([-z, z], axis=1)
        pred = (p1 > 0.5).astype(np.float64)
        return pred, raw, prob

    def contributions(self, params: Any) -> Optional[np.ndarray]:
        if "betas" in params:
            return np.abs(params["betas"]).mean(axis=0)
        return np.abs(params["beta"])
