"""Linear SVM classifier (squared-hinge, L2) via jitted Newton.

Counterpart of OpLinearSVC (reference: core/.../impl/classification/
OpLinearSVC.scala wrapping Spark MLlib LinearSVC - hinge loss + OWLQN).
Squared hinge keeps the objective twice-differentiable so the same
Newton/solve pattern as logistic regression applies (and the same
weight-vector CV fan-out).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import PredictorEstimator


@partial(jax.jit, static_argnames=("iters",))
def _svc_fit_kernel(X, y, w, reg, iters: int = 20):
    n, d = X.shape
    ypm = 2.0 * y - 1.0  # {0,1} -> {-1,+1}
    wsum = jnp.maximum(w.sum(), 1e-12)
    mu = (w @ X) / wsum
    sd = jnp.sqrt(jnp.maximum((w @ (X * X)) / wsum - mu**2, 1e-12))
    Xs = (X - mu) / sd * (w[:, None] > 0)

    def step(carry, _):
        beta, b0 = carry
        margin = ypm * (Xs @ beta + b0)
        active = (margin < 1.0).astype(Xs.dtype) * w
        # squared hinge: L = sum_active (1 - m)^2 / wsum + reg |beta|^2
        r = active * (margin - 1.0) * ypm
        g = (Xs.T @ r) / wsum + 2.0 * reg * beta
        H = (Xs.T @ (Xs * active[:, None])) / wsum + jnp.diag(
            jnp.full((d,), 2.0 * reg + 1e-8)
        )
        g0 = r.sum() / wsum
        h0 = active.sum() / wsum + 1e-8
        delta = jax.scipy.linalg.solve(H, g, assume_a="pos")
        return (beta - delta, b0 - g0 / h0), None

    (beta_s, b0), _ = jax.lax.scan(
        step, (jnp.zeros((d,)), jnp.asarray(0.0)), None, length=iters
    )
    beta = beta_s / sd
    return beta, b0 - (mu * beta).sum()


class OpLinearSVC(PredictorEstimator):
    model_type = "OpLinearSVC"

    def __init__(self, reg_param: float = 0.0, max_iter: int = 20, **kw) -> None:
        super().__init__(**kw)
        self.params.setdefault("reg_param", reg_param)
        self.params.setdefault("max_iter", max_iter)

    def fit_arrays(self, X, y, w=None) -> Any:
        n = len(y)
        w = np.ones(n) if w is None else w
        beta, b0 = _svc_fit_kernel(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(float(self.params.get("reg_param", 0.0))),
            iters=int(self.params.get("max_iter", 20)),
        )
        return {"beta": np.asarray(beta), "intercept": float(b0)}

    def predict_arrays(self, params: Any, X: np.ndarray):
        z = X @ params["beta"] + params["intercept"]
        pred = (z > 0).astype(np.float64)
        raw = np.stack([-z, z], axis=1)
        return pred, raw, None

    def contributions(self, params: Any) -> Optional[np.ndarray]:
        return np.abs(params["beta"])
