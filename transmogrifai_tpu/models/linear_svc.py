"""Linear SVM classifier (squared-hinge, L2) via jitted Newton.

Counterpart of OpLinearSVC (reference: core/.../impl/classification/
OpLinearSVC.scala wrapping Spark MLlib LinearSVC - hinge loss + OWLQN).
Squared hinge keeps the objective twice-differentiable so the same
Newton/solve pattern as logistic regression applies (and the same
weight-vector CV fan-out).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import PredictorEstimator


def svc_newton_core(X, y, w, reg, iters: int = 20,
                    fixed_point: bool = False):
    """Standardization is folded into the algebra (the identities in
    logistic_regression._lr_fit_kernel) so the kernel never materializes a
    standardized copy of X - under vmap over CV fold/grid weight vectors
    every replica reads the SHARED design matrix and adds only O(d^2)
    state.  Un-jitted, dtype-pinned core (see
    logistic_regression.lr_newton_core): ``_svc_fit_kernel`` wraps it
    for dispatch, fused training programs trace it inline."""
    n, d = X.shape
    ypm = 2.0 * y - 1.0  # {0,1} -> {-1,+1}
    wsum = jnp.maximum(w.sum(), 1e-12)
    # global pre-centering + inactive-column exclusion: same f32
    # conditioning fix as logistic_regression._lr_fit_kernel (the folded
    # centered-Gram identity cancels catastrophically when |mean| >> std)
    m0 = X.mean(axis=0)
    X = X - m0
    mu = (w @ X) / wsum
    msq = (w @ (X * X)) / wsum
    var = msq - mu**2
    active = var > 1e-6 * msq + 1e-30
    sd = jnp.where(active, jnp.sqrt(jnp.maximum(var, 1e-12)), 1.0)
    # bf16 Hessian Gram on TPU, f32 gradient/active set: same fixed-point
    # argument as logistic_regression (curvature steers the path only)
    from .logistic_regression import _hessian_bf16

    hess_bf16 = _hessian_bf16()
    Xh = X.astype(jnp.bfloat16) if hess_bf16 else X

    def step(carry):
        beta, b0 = carry  # beta in standardized space
        gamma = beta / sd
        margin = ypm * (X @ gamma + (b0 - mu @ gamma))
        act_rows = (margin < 1.0).astype(X.dtype) * w
        # squared hinge: L = sum_active (1 - m)^2 / wsum + reg |beta|^2
        r = act_rows * (margin - 1.0) * ypm
        sr = r.sum()
        g = ((X.T @ r - mu * sr) / sd / wsum + 2.0 * reg * beta) * active
        if hess_bf16:
            XtAX = jnp.matmul(
                Xh.T, Xh * act_rows.astype(jnp.bfloat16)[:, None],
                preferred_element_type=jnp.float32,
            )
        else:
            XtAX = X.T @ (X * act_rows[:, None])
        a = act_rows @ X
        s = act_rows.sum()
        Hs = (
            XtAX - jnp.outer(mu, a) - jnp.outer(a, mu) + s * jnp.outer(mu, mu)
        ) / jnp.outer(sd, sd) / wsum
        Hs = Hs * jnp.outer(active, active)
        # curvature-relative, dimension-aware PD jitter + guarded step
        # (packed_newton.pd_jitter/guarded_step: shared constants)
        from .packed_newton import guarded_step, pd_jitter

        jitter = pd_jitter(jnp.trace(Hs) / d, d, hess_bf16, base=1e-8)
        H = (
            Hs + jnp.diag(jnp.full((d,), 2.0 * reg))
            + jitter * jnp.eye(d, dtype=X.dtype)
            + jnp.diag((1.0 - active).astype(X.dtype))
        )
        g0 = sr / wsum
        h0 = s / wsum + 1e-8
        delta = guarded_step(
            jax.scipy.linalg.solve(H, g, assume_a="pos"), g
        )
        return beta - delta, b0 - g0 / h0

    from .packed_newton import run_newton

    beta_s, b0 = run_newton(
        step, (jnp.zeros((d,), X.dtype), jnp.zeros((), X.dtype)),
        iters, fixed_point,
    )
    beta = beta_s / sd
    return beta, b0 - ((mu + m0) * beta).sum()


@partial(jax.jit, static_argnames=("iters",))
def _svc_fit_kernel(X, y, w, reg, iters: int = 20):
    """Jitted kernel-at-a-time wrapper over :func:`svc_newton_core`."""
    return svc_newton_core(X, y, w, reg, iters)


def svc_fit_batched_core(X, y, W, regs, iters: int,
                         fixed_point: bool = False):
    """Un-jitted vmapped fold x grid batch (fused-program seam)."""
    return jax.vmap(
        lambda w, r: svc_newton_core(X, y, w, r, iters, fixed_point)
    )(W, regs)


@partial(jax.jit, static_argnames=("iters",))
def _svc_fit_batched(X, y, W, regs, iters: int):
    return svc_fit_batched_core(X, y, W, regs, iters)


class OpLinearSVC(PredictorEstimator):
    #: fused serving seam: predict_arrays (numpy margin) is pure host-side
    lowerable = True
    model_type = "OpLinearSVC"

    def __init__(self, reg_param: float = 0.0, max_iter: int = 20, **kw) -> None:
        super().__init__(**kw)
        self.params.setdefault("reg_param", reg_param)
        self.params.setdefault("max_iter", max_iter)

    def fit_arrays(self, X, y, w=None) -> Any:
        # Spark contract: 'LinearSVC only supports binary classification'
        self._check_binary_labels(y)
        n = len(y)
        w = np.ones(n) if w is None else w
        beta, b0 = _svc_fit_kernel(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(float(self.params.get("reg_param", 0.0))),
            iters=int(self.params.get("max_iter", 20)),
        )
        return {"beta": np.asarray(beta), "intercept": float(b0)}

    def fit_arrays_batched(self, X, y, W, regs, ens):
        """Batched fit: W [B, n] weight masks, regs [B] -> stacked params;
        the whole CV x grid fan-out as one vmapped dispatch (same contract
        as OpLogisticRegression.fit_arrays_batched; SVC has no elastic-net
        term, so ``ens`` is accepted and ignored).  TPU inputs ride the
        MXU-packed explicit batch (packed_newton.py); mesh-sharded inputs
        keep packing via the shard_map Gram."""
        self._check_binary_labels(y)
        from .logistic_regression import _hessian_bf16
        from .packed_newton import (
            packed_mesh_or_none,
            run_packed_guarded,
            svc_fit_batched_packed,
            use_packed,
        )

        iters = int(self.params.get("max_iter", 20))
        if use_packed(X, W):
            mesh = packed_mesh_or_none(X, W)

            def _packed_fit(m, Xa, ya, Wa):
                return svc_fit_batched_packed(
                    jnp.asarray(Xa), jnp.asarray(ya), jnp.asarray(Wa),
                    jnp.asarray(regs), iters=iters,
                    hess_bf16=_hessian_bf16(), mesh=m,
                )

            beta, b0 = run_packed_guarded(
                "svc.packed_gram",
                lambda: _packed_fit(mesh, X, y, W),
                lambda: _packed_fit(
                    None, np.asarray(X), np.asarray(y), np.asarray(W)),
                mesh,
            )
        else:
            beta, b0 = _svc_fit_batched(
                jnp.asarray(X), jnp.asarray(y), jnp.asarray(W),
                jnp.asarray(regs), iters=iters,
            )
        return np.asarray(beta), np.asarray(b0)

    def fused_train_core(self, packed: bool):
        """Fused-training seam (local/fused_train.py): same contract as
        OpLogisticRegression.fused_train_core.  The ranking score mirrors
        ``predict_arrays`` - SVC exposes no probability, so the evaluator
        ranks the 0/1 prediction; the margin sign is computed in f64 like
        the numpy head (only the f64->f32 design-matrix cast differs)."""
        from .logistic_regression import _hessian_bf16

        iters = int(self.params.get("max_iter", 20))
        # trace-time Hessian dtype is part of the program identity
        # (see OpLogisticRegression.fused_train_core)
        hess_bf16 = _hessian_bf16()
        if packed:
            from .packed_newton import svc_fit_batched_packed_core

            def fit(X, y, W, regs, ens):
                return svc_fit_batched_packed_core(
                    X, y, W, regs, iters=iters, hess_bf16=hess_bf16,
                    fixed_point=True,
                )
        else:
            def fit(X, y, W, regs, ens):
                return svc_fit_batched_core(
                    X, y, W, regs, iters, fixed_point=True
                )

        def score(X, beta, b0):
            z = (
                X.astype(jnp.float64) @ beta.astype(jnp.float64)
                + b0.astype(jnp.float64)
            )
            return (z > 0).astype(jnp.float64)

        return {"fit": fit, "score": score,
                "sig": ("svc", iters, packed, hess_bf16)}

    def predict_arrays(self, params: Any, X: np.ndarray):
        z = X @ params["beta"] + params["intercept"]
        pred = (z > 0).astype(np.float64)
        raw = np.stack([-z, z], axis=1)
        return pred, raw, None

    def predict_arrays_xla(self, params: Any, X):
        """jax-traceable mirror of the numpy margin head for the XLA
        fused backend (local/fused_xla.py)."""
        z = X @ jnp.asarray(params["beta"]) + params["intercept"]
        pred = (z > 0).astype(jnp.float64)
        raw = jnp.stack([-z, z], axis=1)
        return pred, raw, None

    def contributions(self, params: Any) -> Optional[np.ndarray]:
        return np.abs(params["beta"])
