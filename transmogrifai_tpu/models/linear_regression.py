"""Linear regression via jitted normal equations / ridge.

Counterpart of OpLinearRegression (reference: core/.../impl/regression/
OpLinearRegression.scala, Spark MLlib WLS/LBFGS internals).  Weighted
ridge solved in closed form: [d, d] Gram matrix built by one MXU matmul,
Cholesky solve on device; elastic-net L1 via reweighted ridge iterations.
vmappable over (weights, lambda) for CV fan-out like the LR kernel.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import PredictorEstimator


def linreg_core(X, y, w, reg, elastic_net, l1_iters: int = 8,
                fixed_point: bool = False):
    """Un-jitted, dtype-pinned closed-form ridge / reweighted-L1 core
    (see logistic_regression.lr_newton_core for the seam contract):
    ``_linreg_fit_kernel`` wraps it for dispatch, fused training
    programs trace it inline."""
    n, d = X.shape
    wsum = w.sum()
    # global pre-centering + inactive-column exclusion: same f32
    # conditioning fix as logistic_regression._lr_fit_kernel
    m0 = X.mean(axis=0)
    X = X - m0
    mu = (w @ X) / wsum
    msq = (w @ (X * X)) / wsum
    var = msq - mu**2
    active = var > 1e-6 * msq + 1e-30
    sd = jnp.where(active, jnp.sqrt(jnp.maximum(var, 1e-12)), 1.0)
    ybar = (w @ y) / wsum

    lam_l2 = reg * (1.0 - elastic_net)
    lam_l1 = reg * elastic_net
    # standardized Gram/moment derived from raw-space reductions (no [n, d]
    # standardized temporary; see the logistic kernel for the identities)
    XtWX = X.T @ (X * w[:, None])
    a = w @ X
    G = (
        XtWX - jnp.outer(mu, a) - jnp.outer(a, mu) + wsum * jnp.outer(mu, mu)
    ) / jnp.outer(sd, sd) / wsum
    G = G * jnp.outer(active, active)
    r = w * (y - ybar)
    c = (((X.T @ r) - mu * r.sum()) / sd / wsum) * active

    # dimension-aware f32 ridge (Cholesky rounding ~eps*d*||G||), same
    # hardening as the logistic kernels; G is fixed so it prices once
    from .packed_newton import pd_jitter

    ridge = pd_jitter(jnp.trace(G) / d, d, hess_bf16=False)

    def step(beta):
        l1_diag = lam_l1 / (jnp.abs(beta) + 1e-3)
        H = G + jnp.diag(
            lam_l2 + l1_diag + ridge + (1.0 - active).astype(X.dtype)
        )
        new = jax.scipy.linalg.solve(H, c, assume_a="pos")
        return jnp.where(jnp.isfinite(new), new, beta)

    from .packed_newton import run_newton

    beta_s = run_newton(step, jnp.zeros((d,), X.dtype), l1_iters,
                        fixed_point)
    beta = beta_s / sd
    intercept = ybar - ((mu + m0) * beta).sum()
    return beta, intercept


@partial(jax.jit, static_argnames=("l1_iters",))
def _linreg_fit_kernel(X, y, w, reg, elastic_net, l1_iters: int = 8):
    """Jitted kernel-at-a-time wrapper over :func:`linreg_core`."""
    return linreg_core(X, y, w, reg, elastic_net, l1_iters)


def linreg_fit_batched_core(X, y, W, regs, ens, fixed_point: bool = False):
    """Un-jitted vmapped fold x grid batch (fused-program seam)."""
    return jax.vmap(
        lambda w, reg, en: linreg_core(X, y, w, reg, en,
                                       fixed_point=fixed_point),
    )(W, regs, ens)


_linreg_fit_batched = jax.jit(
    lambda X, y, W, regs, ens: linreg_fit_batched_core(X, y, W, regs, ens)
)


@jax.jit
def _linreg_predict_kernel(X, beta, intercept):
    return X @ beta + intercept


class OpLinearRegression(PredictorEstimator):
    """(reference: OpLinearRegression.scala; grid: regParam
    {0.001,0.01,0.1,0.2}, elasticNet {0.1,0.5})"""

    #: fused serving seam: predict_arrays_np is pure numpy over host betas
    lowerable = True

    model_type = "OpLinearRegression"
    batched_needs_binary_y = False  # squared loss: any real y batches fine

    def __init__(
        self,
        reg_param: float = 0.0,
        elastic_net_param: float = 0.0,
        fit_intercept: bool = True,
        **kw,
    ) -> None:
        super().__init__(**kw)
        self.params.setdefault("reg_param", reg_param)
        self.params.setdefault("elastic_net_param", elastic_net_param)
        self.params.setdefault("fit_intercept", fit_intercept)

    def fit_arrays(self, X, y, w=None):
        n = len(y)
        w = np.ones(n) if w is None else w
        beta, b0 = _linreg_fit_kernel(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(float(self.params["reg_param"])),
            jnp.asarray(float(self.params["elastic_net_param"])),
        )
        return {"beta": np.asarray(beta), "intercept": float(b0)}

    def fit_arrays_batched(self, X, y, W, regs, ens):
        """TPU inputs ride the MXU-packed explicit batch (packed_newton.py:
        the fixed fold-mask Gram runs ONCE as a packed matmul, the l1 scan
        is [B, d, d] solves only); mesh-sharded inputs keep packing via
        the shard_map Gram."""
        from .packed_newton import (
            linreg_fit_batched_packed,
            packed_mesh_or_none,
            run_packed_guarded,
            use_packed,
        )

        if use_packed(X, W):
            mesh = packed_mesh_or_none(X, W)

            def _packed_fit(m, Xa, ya, Wa):
                return linreg_fit_batched_packed(
                    jnp.asarray(Xa), jnp.asarray(ya), jnp.asarray(Wa),
                    jnp.asarray(regs), jnp.asarray(ens), mesh=m,
                )

            beta, b0 = run_packed_guarded(
                "linreg.packed_gram",
                lambda: _packed_fit(mesh, X, y, W),
                lambda: _packed_fit(
                    None, np.asarray(X), np.asarray(y), np.asarray(W)),
                mesh,
            )
        else:
            beta, b0 = _linreg_fit_batched(
                jnp.asarray(X), jnp.asarray(y), jnp.asarray(W),
                jnp.asarray(regs), jnp.asarray(ens),
            )
        return np.asarray(beta), np.asarray(b0)

    def fused_train_core(self, packed: bool):
        """Fused-training seam (local/fused_train.py): same contract as
        OpLogisticRegression.fused_train_core.  The 'score' is the raw
        prediction (regression evaluators consume it directly), computed
        as the same f32 matvec ``_linreg_predict_kernel`` runs."""
        if packed:
            from .packed_newton import linreg_fit_batched_packed_core

            def fit(X, y, W, regs, ens):
                return linreg_fit_batched_packed_core(
                    X, y, W, regs, ens, fixed_point=True
                )
        else:
            def fit(X, y, W, regs, ens):
                return linreg_fit_batched_core(
                    X, y, W, regs, ens, fixed_point=True
                )

        def score(X, beta, b0):
            return X @ beta + b0

        return {"fit": fit, "score": score, "sig": ("linreg", packed)}

    # -- streamed sufficient-statistics fit (readers/pipeline.py) ----------
    @staticmethod
    def streaming_fit_stats(X_block, y_block) -> tuple:
        """Per-chunk sufficient statistics for the closed-form ridge fit:
        (n, Σx [d], XᵀX [d, d], Σy, Xᵀy [d]).  Mergeable by addition, so
        the sharded input pipeline can accumulate them in its workers
        WHILE later shards parse — fit_from_stats then completes the
        ingest→fit overlap in O(d²) after the last chunk lands."""
        Xb = np.asarray(X_block, dtype=np.float64)
        yb = np.asarray(y_block, dtype=np.float64)
        return (
            len(yb), Xb.sum(axis=0), Xb.T @ Xb, float(yb.sum()),
            Xb.T @ yb,
        )

    def fit_from_stats(self, stats) -> dict:
        """Fit from accumulated :meth:`streaming_fit_stats` chunks —
        the same centered/standardized ridge + reweighted-L1 math as
        ``_linreg_fit_kernel``, reconstructed from the merged moments
        (the [n, d] matrix never needs to exist).  Chunks are summed in
        the given (deterministic source) order.  Parity with the batch
        kernel is f32-level, pinned in tests."""
        from .packed_newton import pd_jitter

        stats = list(stats)
        if not stats:
            raise ValueError("fit_from_stats needs at least one chunk")
        n = sum(s[0] for s in stats)
        S1 = np.sum([s[1] for s in stats], axis=0)
        S2 = np.sum([s[2] for s in stats], axis=0)
        Sy = float(sum(s[3] for s in stats))
        Sxy = np.sum([s[4] for s in stats], axis=0)
        d = len(S1)
        m0 = S1 / n
        # centered second moments: Xcᵀ Xc = XᵀX - n·m0 m0ᵀ (Xc sums to 0,
        # so the kernel's `mu`/`a` terms vanish exactly here)
        XtX_c = S2 - n * np.outer(m0, m0)
        var = np.maximum(np.diag(XtX_c) / n, 0.0)
        msq = var  # mu == 0
        active = var > 1e-6 * msq + 1e-30
        sd = np.where(active, np.sqrt(np.maximum(var, 1e-12)), 1.0)
        ybar = Sy / n
        G = (XtX_c / np.outer(sd, sd) / n) * np.outer(active, active)
        c = ((Sxy - m0 * Sy) / sd / n) * active
        reg = float(self.params["reg_param"])
        en = float(self.params["elastic_net_param"])
        lam_l2 = reg * (1.0 - en)
        lam_l1 = reg * en
        ridge = float(pd_jitter(np.trace(G) / d, d, hess_bf16=False))
        beta_s = np.zeros(d)
        for _ in range(8):  # same reweighted-L1 schedule as the kernel
            l1_diag = lam_l1 / (np.abs(beta_s) + 1e-3)
            H = G + np.diag(lam_l2 + l1_diag + ridge + (1.0 - active))
            new = np.linalg.solve(H, c)
            beta_s = np.where(np.isfinite(new), new, beta_s)
        beta = beta_s / sd
        intercept = ybar - float(m0 @ beta)
        return {"beta": beta, "intercept": float(intercept)}

    def predict_arrays(self, params: Any, X: np.ndarray):
        pred = np.asarray(
            _linreg_predict_kernel(
                jnp.asarray(X), jnp.asarray(params["beta"]),
                jnp.asarray(params["intercept"]),
            )
        )
        return pred, None, None

    def predict_arrays_np(self, params: Any, X: np.ndarray):
        pred = (X @ params["beta"] + params["intercept"]).astype(np.float64)
        return pred, None, None

    def predict_arrays_xla(self, params: Any, X):
        """jax-traceable mirror of the numpy head for the XLA fused
        backend (local/fused_xla.py)."""
        pred = (X @ jnp.asarray(params["beta"])
                + params["intercept"]).astype(jnp.float64)
        return pred, None, None

    def contributions(self, params: Any) -> Optional[np.ndarray]:
        return np.abs(params["beta"])
