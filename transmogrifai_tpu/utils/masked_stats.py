"""Masked column statistics helpers.

Null-aware reductions over (values, mask) pairs - the columnar counterpart of
the reference's SequenceAggregators (reference: utils/.../spark/
SequenceAggregators.scala:41-212: SumNumSeq, MeanSeqNullNum, ModeSeqNullInt).
All reductions ignore masked-out entries; shapes are static so the same code
jits on TPU.
"""
from __future__ import annotations

import numpy as np


def masked_mean(values: np.ndarray, mask: np.ndarray, default: float = 0.0) -> float:
    n = mask.sum()
    if n == 0:
        return default
    return float(values[mask].sum() / n)


def masked_mode(values: np.ndarray, mask: np.ndarray, default: float = 0.0) -> float:
    """Most frequent value among present entries; ties -> smallest value
    (reference ModeSeqNullInt picks min on ties)."""
    present = values[mask]
    if present.size == 0:
        return default
    uniq, counts = np.unique(present, return_counts=True)
    return float(uniq[np.argmax(counts)])  # np.unique sorts -> min on ties


def masked_variance(values: np.ndarray, mask: np.ndarray) -> float:
    present = values[mask]
    if present.size < 2:
        return 0.0
    return float(present.var(ddof=1))


def masked_min_max(values: np.ndarray, mask: np.ndarray) -> tuple[float, float]:
    present = values[mask]
    if present.size == 0:
        return (0.0, 0.0)
    return float(present.min()), float(present.max())
