"""Unique id generation for features and stages.

TPU-native counterpart of the reference's ``UID`` generator
(reference: utils/src/main/scala/com/salesforce/op/utils/UID.scala:40-50):
sequential per-class counters so ids are deterministic within a process,
plus a reset hook used by tests for reproducible DAG construction.
"""
from __future__ import annotations

import itertools
import threading
from collections import defaultdict

_lock = threading.Lock()
_counters: dict[str, itertools.count] = defaultdict(lambda: itertools.count(0))


def make_uid(prefix: str) -> str:
    """Return a deterministic sequential uid like ``Real_003``."""
    with _lock:
        n = next(_counters[prefix])
    return f"{prefix}_{n:09x}"


def reset_uids() -> None:
    """Reset all counters (test use only, mirrors UID.reset in the reference)."""
    with _lock:
        _counters.clear()
