"""MurmurHash3 (x86_32) and hashing-TF helpers.

Counterpart of the reference's hashing stack (reference: core/.../impl/
feature/OPCollectionHashingVectorizer.scala:42,76-86 using
mllib.feature.HashingTF with murmur3, seed 42).  Pure-python murmur3 here
for correctness; the batch path vectorizes over tokens and is replaced by a
C++ kernel for bulk ingest (native/ directory) when available.
"""
from __future__ import annotations

import numpy as np

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_MASK = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _MASK


def murmur3_32(data: bytes, seed: int = 42) -> int:
    """murmur3_x86_32 over bytes; returns unsigned 32-bit int."""
    h = seed & _MASK
    n = len(data)
    n4 = n & ~0x3
    for i in range(0, n4, 4):
        k = int.from_bytes(data[i : i + 4], "little")
        k = (k * _C1) & _MASK
        k = _rotl32(k, 15)
        k = (k * _C2) & _MASK
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & _MASK
    k = 0
    tail = data[n4:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * _C1) & _MASK
        k = _rotl32(k, 15)
        k = (k * _C2) & _MASK
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK
    h ^= h >> 16
    return h


def hash_token(token: str, num_features: int, seed: int = 42) -> int:
    return murmur3_32(token.encode("utf-8"), seed) % num_features


def hashing_tf(
    token_lists: list[list[str]],
    num_features: int,
    seed: int = 42,
    binary: bool = False,
) -> np.ndarray:
    """Term-frequency hashing of tokenized rows -> dense [n, num_features]."""
    out = np.zeros((len(token_lists), num_features), dtype=np.float32)
    cache: dict[str, int] = {}
    for i, toks in enumerate(token_lists):
        for t in toks:
            j = cache.get(t)
            if j is None:
                j = hash_token(t, num_features, seed)
                cache[t] = j
            if binary:
                out[i, j] = 1.0
            else:
                out[i, j] += 1.0
    return out
