"""ctypes bridge to the C++ host kernels (native/txkernels.cpp).

Builds the shared library on first use (g++ is in the image) and falls back
to the pure-python implementations when compilation is unavailable.  The
C++ side replaces the reference's JVM text crunching (murmur3 HashingTF +
Lucene analyzers - see native/txkernels.cpp header for citations) on the
host side of the TPU pipeline.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "txkernels.cpp")
_SRC_TREES = os.path.join(_REPO_ROOT, "native", "txtrees.cpp")
_LIB = os.path.join(_REPO_ROOT, "native", "libtxkernels.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    srcs = [s for s in (_SRC, _SRC_TREES) if os.path.exists(s)]
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-pthread", *srcs, "-o", _LIB],
            check=True, capture_output=True, timeout=240,
        )
        return True
    except Exception:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    from ..faults import injection as _faults

    if _faults.inject_unavailable("native.load"):
        # fault drill: the shared library "fails to load" on this call;
        # checked BEFORE the memo so the degradation is per-call and the
        # process recovers the real lib once the drill disarms
        return None
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        stale = any(
            os.path.exists(s) and os.path.getmtime(s) > os.path.getmtime(_LIB)
            for s in (_SRC, _SRC_TREES)
        ) if os.path.exists(_LIB) else True
        if stale:
            built = any(
                os.path.exists(s) for s in (_SRC, _SRC_TREES)
            ) and _build()
            # a stale-but-present .so is still usable if the rebuild failed
            # (e.g. no g++ on the serving host)
            if not built and not os.path.exists(_LIB):
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        lib.tx_murmur3_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_uint32, ctypes.c_void_p,
        ]
        lib.tx_tokenize_hash_tf.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_uint32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_void_p,
        ]
        lib.tx_parse_doubles.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        try:  # CSV scan kernels (may be absent in a stale lib)
            lib.tx_csv_index.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ]
            lib.tx_csv_index.restype = ctypes.c_int64
            lib.tx_csv_cells.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p,
            ]
        except AttributeError:
            pass
        try:  # GIL-free byte counting (sharded-pipeline workers)
            lib.tx_count_byte.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ]
            lib.tx_count_byte.restype = ctypes.c_int64
            lib.tx_set_csv_threads.argtypes = [ctypes.c_int64]
            lib.tx_set_csv_threads.restype = None
        except AttributeError:
            pass
        try:  # tree learner entry points (native/txtrees.cpp)
            lib.tx_fit_forest_hist.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_double, ctypes.c_double,
                ctypes.c_double, ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p,
            ]
            lib.tx_fit_gbt_hist.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_double, ctypes.c_double, ctypes.c_double,
                ctypes.c_double,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p,
            ]
            lib.tx_predict_forest_hist.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p,
            ]
            lib.tx_bin_data.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p,
            ]
        except AttributeError:  # stale lib without the tree symbols
            pass
        _lib = lib
        return _lib


def has_tree_symbols() -> bool:
    lib = get_lib()
    return lib is not None and hasattr(lib, "tx_fit_forest_hist")


def pack_strings(values: Sequence[Optional[str]]) -> tuple[np.ndarray, np.ndarray]:
    """Pack optional strings into (utf-8 byte buffer, offsets[n+1])."""
    encoded = [v.encode("utf-8") if v else b"" for v in values]
    offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in encoded], out=offsets[1:])
    data = np.frombuffer(b"".join(encoded), dtype=np.uint8).copy()
    if data.size == 0:
        data = np.zeros(1, dtype=np.uint8)
    return data, offsets


def murmur3_batch(values: Sequence[Optional[str]], seed: int = 42) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    data, offsets = pack_strings(values)
    out = np.zeros(len(values), dtype=np.uint32)
    lib.tx_murmur3_batch(
        data.ctypes.data, offsets.ctypes.data, len(values),
        np.uint32(seed), out.ctypes.data,
    )
    return out


def tokenize_hash_tf(
    values: Sequence[Optional[str]],
    dims: int,
    seed: int = 42,
    min_token_length: int = 1,
    binary: bool = False,
) -> Optional[np.ndarray]:
    """Fused tokenize+hash TF; None if the native lib is unavailable.

    The C++ kernel is byte-oriented: it lowercases ASCII only, treats
    every >=0x80 byte as a word char (so emoji survive where python's
    unicode \\w drops them), and hashes tokens from a 4096-byte buffer.
    Rows where those shortcuts could diverge from the python tokenizer -
    any non-ASCII character, or length past the token buffer - are
    recomputed on the exact python path, so the SAME text hashes to the
    SAME slots with or without the native lib (cross-backend model
    portability).  Pure-ASCII rows (the hot path) stay native.
    """
    lib = get_lib()
    if lib is None:
        return None
    needs_py = [
        i for i, v in enumerate(values)
        if v is not None and (len(v) > 4096 or not v.isascii())
    ]
    if needs_py:
        # blank the python-bound rows BEFORE the native call so the
        # kernel does no work whose output gets overwritten
        py_set = set(needs_py)
        native_vals: Sequence[Optional[str]] = [
            None if i in py_set else v for i, v in enumerate(values)
        ]
    else:
        native_vals = values
    data, offsets = pack_strings(native_vals)
    out = np.zeros((len(values), dims), dtype=np.float32)
    lib.tx_tokenize_hash_tf(
        data.ctypes.data, offsets.ctypes.data, len(values),
        np.int32(dims), np.uint32(seed), np.int32(min_token_length),
        np.int32(1 if binary else 0), out.ctypes.data,
    )
    if needs_py:
        from ..ops.text import tokenize
        from .hashing import hashing_tf

        exact = hashing_tf(
            [tokenize(values[i], True, min_token_length) for i in needs_py],
            dims, seed=seed, binary=binary,
        )
        out[needs_py] = exact
    return out


def csv_scan(
    buf: bytes, ncols: int, modes: np.ndarray
) -> Optional[tuple[int, np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """Quote-aware CSV scan of one byte chunk via the C++ kernels.

    ``modes`` [ncols] uint8 selects per-column work: 0 = skip, 1 =
    numeric parse, 2 = text cell offsets (bool arrays are accepted as
    numeric-vs-skip for convenience).  Returns (nrows, num_vals
    [ncols, nrows] f64, num_mask [ncols, nrows] bool, cell_begin
    [ncols, nrows] i64, cell_end) - column-major so each column is a
    contiguous slice; the offset arrays are 0-row dummies when no text
    column was requested - or None when the native lib (or the CSV
    symbols) is unavailable.
    """
    lib = get_lib()
    if lib is None or not hasattr(lib, "tx_csv_index"):
        return None
    modes8 = np.ascontiguousarray(modes, dtype=np.uint8)
    data = np.frombuffer(buf, dtype=np.uint8)
    if data.size == 0:
        z = np.zeros((ncols, 0))
        return 0, z, z.astype(bool), z.astype(np.int64), z.astype(np.int64)
    cap = count_byte(buf, 0x0A)
    if cap is None:  # stale lib without the symbol
        cap = int(np.count_nonzero(data == 0x0A))
    cap += 1
    row_starts = np.zeros(cap, dtype=np.int64)
    nrows = int(
        lib.tx_csv_index(data.ctypes.data, data.size, row_starts.ctypes.data)
    )
    any_mat = bool((modes8 != 0).any())
    num_vals = np.zeros((ncols, nrows), dtype=np.float64)
    num_mask = np.zeros((ncols, nrows), dtype=np.uint8)
    # the kernel records offsets for EVERY materialized column (numeric
    # included, feeding the unicode float() retry); slot indexing is
    # col*nrows, so the buffer is full-shape when anything materializes
    off_rows = nrows if any_mat else 0
    cell_begin = np.zeros((ncols, off_rows), dtype=np.int64)
    cell_end = np.zeros((ncols, off_rows), dtype=np.int64)
    lib.tx_csv_cells(
        data.ctypes.data, data.size, row_starts.ctypes.data, nrows,
        np.int32(ncols), modes8.ctypes.data, num_vals.ctypes.data,
        num_mask.ctypes.data, cell_begin.ctypes.data, cell_end.ctypes.data,
    )
    return nrows, num_vals, num_mask.astype(bool), cell_begin, cell_end


def count_byte(buf: bytes, byte: int) -> Optional[int]:
    """Count occurrences of one byte WITHOUT holding the GIL (ctypes
    releases it for the native call) — the sharded input pipeline's
    workers use this for the quote-parity and newline scans that
    ``bytes.count`` would serialize.  None when the lib lacks the
    symbol (callers fall back to bytes.count)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "tx_count_byte"):
        return None
    if not buf:
        return 0
    return int(lib.tx_count_byte(buf, len(buf), int(byte)))


def set_csv_threads(n: int) -> bool:
    """Install (n >= 1) or clear (n = 0) the dynamic per-scan thread cap
    for ``tx_csv_cells`` — an atomic the kernel reads, NOT an environment
    mutation (setenv while another thread's scan getenv()s is
    use-after-free UB).  The sharded input pipeline caps fan-out through
    this while its workers run.  Returns False when the lib (or symbol)
    is unavailable."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "tx_set_csv_threads"):
        return False
    lib.tx_set_csv_threads(int(n))
    return True


def parse_doubles(values: Sequence[Optional[str]]) -> Optional[tuple[np.ndarray, np.ndarray]]:
    lib = get_lib()
    if lib is None:
        return None
    data, offsets = pack_strings(values)
    out = np.zeros(len(values), dtype=np.float64)
    mask = np.zeros(len(values), dtype=np.uint8)
    lib.tx_parse_doubles(
        data.ctypes.data, offsets.ctypes.data, len(values),
        out.ctypes.data, mask.ctypes.data,
    )
    return out, mask.astype(bool)
