"""Statistical helpers: contingency-table association measures.

Counterpart of OpStatistics (reference: utils/.../stats/OpStatistics.scala:384
- chiSquaredTest/CramersV, pointwise mutual information, association-rule
max confidence/support, computeCorrelationsWithLabel).  Contingency tables
arrive as dense [n_label_classes, n_categories] count matrices (built by one
matmul on device); everything here is cheap host math on those small tables.
"""
from __future__ import annotations

import numpy as np


def chi_squared(contingency: np.ndarray) -> float:
    """Pearson chi-squared statistic of a contingency table."""
    c = np.asarray(contingency, dtype=np.float64)
    total = c.sum()
    if total == 0:
        return 0.0
    row = c.sum(axis=1, keepdims=True)
    col = c.sum(axis=0, keepdims=True)
    expected = row @ col / total
    with np.errstate(divide="ignore", invalid="ignore"):
        terms = np.where(expected > 0, (c - expected) ** 2 / expected, 0.0)
    return float(terms.sum())


def cramers_v(contingency: np.ndarray) -> float:
    """Cramer's V with the reference's bias handling: V = sqrt(chi2 / (n *
    min(r-1, c-1))) over columns/rows that are non-empty (reference:
    OpStatistics.cramersV - empty rows/cols are filtered before the test)."""
    c = np.asarray(contingency, dtype=np.float64)
    c = c[c.sum(axis=1) > 0][:, c.sum(axis=0) > 0] if c.size else c
    if c.size == 0 or min(c.shape) < 2:
        return 0.0
    n = c.sum()
    dof = min(c.shape[0] - 1, c.shape[1] - 1)
    if n == 0 or dof == 0:
        return 0.0
    v2 = chi_squared(c) / (n * dof)
    return float(np.sqrt(max(v2, 0.0)))


def pointwise_mutual_info(contingency: np.ndarray) -> np.ndarray:
    """PMI per cell in log2 (reference: OpStatistics contingencyStats PMI):
    pmi[i,j] = log2( p(i,j) / (p(i) p(j)) ); zero cells -> 0."""
    c = np.asarray(contingency, dtype=np.float64)
    total = c.sum()
    if total == 0:
        return np.zeros_like(c)
    p = c / total
    pr = p.sum(axis=1, keepdims=True)
    pc = p.sum(axis=0, keepdims=True)
    with np.errstate(divide="ignore", invalid="ignore"):
        pmi = np.log2(p / (pr @ pc))
    pmi[~np.isfinite(pmi)] = 0.0
    return pmi


def max_rule_confidences(contingency: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Association rule category -> label-class: per category (column),
    confidence = max_i c[i,j]/colsum_j and support = colsum_j / n
    (reference: OpStatistics.maxConfidences)."""
    c = np.asarray(contingency, dtype=np.float64)
    n = c.sum()
    colsum = c.sum(axis=0)
    with np.errstate(divide="ignore", invalid="ignore"):
        conf = np.where(colsum > 0, c.max(axis=0) / colsum, 0.0)
    support = colsum / n if n > 0 else np.zeros_like(colsum)
    return conf, support


def average_ranks(a: np.ndarray) -> np.ndarray:
    """Average (fractional) ranks, 1-based, ties averaged - scipy
    rankdata(method='average') semantics, vectorized per column for 2-D
    input.  Host-side by design: Spearman runs under the SanityChecker
    sample cap (<= 1M rows), where host ranking is cheap and sort-free
    device ranking is not (TPU sorts at [n, d] scale are pathologically
    slow - see the rank-metric kernel's design notes)."""
    a = np.asarray(a, dtype=np.float64)
    if a.ndim == 1:
        return _average_ranks_1d(a)
    out = np.empty_like(a)
    for j in range(a.shape[1]):
        out[:, j] = _average_ranks_1d(a[:, j])
    return out


def _average_ranks_1d(v: np.ndarray) -> np.ndarray:
    order = np.argsort(v, kind="stable")
    sv = v[order]
    new_group = np.r_[True, sv[1:] != sv[:-1]]
    group_ids = np.cumsum(new_group) - 1
    firsts = np.nonzero(new_group)[0]
    counts = np.diff(np.r_[firsts, len(v)])
    avg = firsts + (counts - 1) / 2.0 + 1.0  # 1-based average rank per group
    ranks = np.empty(len(v), dtype=np.float64)
    ranks[order] = avg[group_ids]
    return ranks


def pearson_correlation(
    x_sum: np.ndarray,
    x_sq_sum: np.ndarray,
    xy_sum: np.ndarray,
    y_sum: float,
    y_sq_sum: float,
    n: float,
) -> np.ndarray:
    """Column-wise Pearson correlation with a label from moment sums
    (single-pass, psum-friendly).  NaN where variance is 0 (matching
    Spark's Statistics.corr behavior of NaN for constant columns)."""
    cov = xy_sum / n - (x_sum / n) * (y_sum / n)
    vx = x_sq_sum / n - (x_sum / n) ** 2
    vy = y_sq_sum / n - (y_sum / n) ** 2
    with np.errstate(divide="ignore", invalid="ignore"):
        corr = cov / np.sqrt(vx * vy)
    return corr
