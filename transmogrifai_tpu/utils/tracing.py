"""Per-stage execution metrics.

Counterpart of OpSparkListener / AppMetrics / StageMetrics (reference:
utils/.../spark/OpSparkListener.scala:56-161): structured per-stage
wall-clock + row-count records accumulated during fit/transform, with the
same structured-log-line style, retrievable at the end of a run.  The JAX
profiler (jax.profiler.trace) fills the deep-tracing role the Spark UI
played; ``profile_to`` wraps a block with an xplane dump.
"""
from __future__ import annotations

import contextlib
import logging
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional

log = logging.getLogger("transmogrifai_tpu.metrics")

LOG_PREFIX = "op_stage_metrics"

# -- mesh resilience surfacing ----------------------------------------------
# parallel/resilience registers its MeshTelemetry event feed here so
# collective detection/retry/shrink events ride the same stage-metrics
# channel (and model.summary_json()) without tracing importing any
# jax-heavy module - this file must stay importable before jax/numpy init.
_mesh_events_source = None


def register_mesh_events_source(fn) -> None:
    """``fn(since_epoch=None) -> list[dict]`` of mesh resilience events
    (detections, straggler retries, shrink-to-survivors recomputes);
    ``since_epoch`` scopes the feed to one run's window."""
    global _mesh_events_source
    _mesh_events_source = fn


def mesh_events(since_epoch=None) -> list:
    if _mesh_events_source is None:
        return []
    try:
        return list(_mesh_events_source(since_epoch))
    except Exception as e:  # telemetry must never break metrics reporting
        log.debug("mesh event source failed: %s", e)
        return []


@dataclass
class StageMetrics:
    stage_uid: str
    operation: str
    phase: str  # 'fit' | 'transform'
    wall_s: float
    n_rows: int
    extra: dict = field(default_factory=dict)

    def log_line(self) -> str:
        kv = {
            "uid": self.stage_uid,
            "op": self.operation,
            "phase": self.phase,
            "wall_s": f"{self.wall_s:.4f}",
            "rows": self.n_rows,
            **self.extra,
        }
        return LOG_PREFIX + " " + " ".join(f"{k}={v}" for k, v in kv.items())

    def to_json(self) -> dict:
        return {
            "stage_uid": self.stage_uid,
            "operation": self.operation,
            "phase": self.phase,
            "wall_s": self.wall_s,
            "n_rows": self.n_rows,
            **self.extra,
        }


@dataclass
class AppMetrics:
    """Whole-run accumulation (reference: AppMetrics, OpSparkListener.scala:
    133-161)."""

    stages: list[StageMetrics] = field(default_factory=list)
    start_time: float = field(default_factory=time.time)

    def record(self, m: StageMetrics) -> None:
        self.stages.append(m)
        log.info(m.log_line())

    @contextlib.contextmanager
    def timed(self, stage, phase: str, n_rows: int) -> Iterator[None]:
        t0 = time.time()
        try:
            yield
        finally:
            self.record(
                StageMetrics(
                    stage_uid=stage.uid,
                    operation=stage.operation_name,
                    phase=phase,
                    wall_s=time.time() - t0,
                    n_rows=n_rows,
                )
            )

    @property
    def total_wall_s(self) -> float:
        return time.time() - self.start_time

    def by_operation(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for m in self.stages:
            out[m.operation] = out.get(m.operation, 0.0) + m.wall_s
        return dict(sorted(out.items(), key=lambda kv: -kv[1]))

    def to_json(self) -> dict:
        out = {
            "total_wall_s": self.total_wall_s,
            "stages": [m.to_json() for m in self.stages],
            "by_operation": self.by_operation(),
        }
        # degraded-mode events (collective stalls, straggler retries,
        # shrink-to-survivors recomputes) belong next to the stage walls
        # they inflated - scoped to THIS run's window so one model's
        # summary never reports another run's degradation
        ev = mesh_events(since_epoch=self.start_time)
        if ev:
            out["mesh_resilience_events"] = ev
        return out


def percentiles(
    values, qs: tuple = (50.0, 95.0, 99.0)
) -> dict[str, float]:
    """Empirical percentiles keyed 'p50'/'p95'/'p99' (linear interpolation
    between order statistics).  The shared latency-summary helper behind
    the serving telemetry (serving/telemetry.py) - dependency-light on
    purpose so tracing stays importable before jax/numpy init."""
    out: dict[str, float] = {}
    vals = sorted(float(v) for v in values)
    for q in qs:
        key = f"p{q:g}"
        if not vals:
            out[key] = float("nan")
            continue
        pos = (len(vals) - 1) * (q / 100.0)
        lo = int(pos)
        hi = min(lo + 1, len(vals) - 1)
        out[key] = vals[lo] + (vals[hi] - vals[lo]) * (pos - lo)
    return out


@contextlib.contextmanager
def profile_to(path: Optional[str]) -> Iterator[None]:
    """Wrap a block in a JAX profiler trace (xplane dump readable by
    tensorboard/xprof) when ``path`` is set."""
    if not path:
        yield
        return
    import jax

    with jax.profiler.trace(path):
        yield
