"""Per-stage execution metrics.

Counterpart of OpSparkListener / AppMetrics / StageMetrics (reference:
utils/.../spark/OpSparkListener.scala:56-161): structured per-stage
wall-clock + row-count records accumulated during fit/transform, with the
same structured-log-line style, retrievable at the end of a run.  The JAX
profiler (jax.profiler.trace) fills the deep-tracing role the Spark UI
played; ``profile_to`` wraps a block with an xplane dump.

Since ISSUE 7 this module is a THIN layer over the unified observability
plane (``transmogrifai_tpu/obs/``): quantiles come from the one shared
implementation (:func:`transmogrifai_tpu.obs.metrics.percentiles` -
``percentiles`` here is an alias kept for the many existing callers),
``AppMetrics.timed`` additionally records a trace span per stage
fit/transform so per-stage walls ride the run's span tree, and each
``AppMetrics`` registers itself as a metrics-registry view.  Both this
module and ``obs/`` stay importable before jax/numpy init.
"""
from __future__ import annotations

import contextlib
import logging
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional

from ..obs import metrics as _obs_metrics
from ..obs import trace as _obs_trace

log = logging.getLogger("transmogrifai_tpu.metrics")

LOG_PREFIX = "op_stage_metrics"

#: THE quantile implementation lives in obs/metrics.py now; this alias
#: keeps every existing ``utils.tracing.percentiles`` caller working
#: (tests pin the two names identical)
percentiles = _obs_metrics.percentiles

# -- mesh resilience surfacing ----------------------------------------------
# parallel/resilience registers its MeshTelemetry event feed here so
# collective detection/retry/shrink events ride the same stage-metrics
# channel (and model.summary_json()) without tracing importing any
# jax-heavy module - this file must stay importable before jax/numpy init.
_mesh_events_source = None


def register_mesh_events_source(fn) -> None:
    """``fn(since_epoch=None) -> list[dict]`` of mesh resilience events
    (detections, straggler retries, shrink-to-survivors recomputes);
    ``since_epoch`` scopes the feed to one run's window."""
    global _mesh_events_source
    _mesh_events_source = fn


def mesh_events_dropped() -> int:
    """How many times the mesh event feed failed to deliver (the
    ``obs.events_dropped`` self-metric): a broken feed must be VISIBLE
    in snapshots, not an invisible hole in the degradation report."""
    return int(
        _obs_metrics.metrics_registry().counter("obs.events_dropped").value
    )


def mesh_events(since_epoch=None) -> list:
    if _mesh_events_source is None:
        return []
    try:
        return list(_mesh_events_source(since_epoch))
    except Exception as e:  # telemetry must never break metrics
        # reporting - but a silently-broken event feed is exactly the
        # invisible degradation ISSUE 7 forbids: count the drop in the
        # obs self-metric (surfaced by AppMetrics.to_json) and log loud
        _obs_metrics.metrics_registry().counter(
            "obs.events_dropped",
            help="telemetry event-feed failures (a broken feed, not an "
                 "empty one)",
        ).inc()
        log.warning("mesh event source failed (drop counted in "
                    "obs.events_dropped): %s", e)
        return []


@dataclass
class StageMetrics:
    stage_uid: str
    operation: str
    phase: str  # 'fit' | 'transform'
    wall_s: float
    n_rows: int
    extra: dict = field(default_factory=dict)

    def log_line(self) -> str:
        kv = {
            "uid": self.stage_uid,
            "op": self.operation,
            "phase": self.phase,
            "wall_s": f"{self.wall_s:.4f}",
            "rows": self.n_rows,
            **self.extra,
        }
        return LOG_PREFIX + " " + " ".join(f"{k}={v}" for k, v in kv.items())

    def to_json(self) -> dict:
        return {
            "stage_uid": self.stage_uid,
            "operation": self.operation,
            "phase": self.phase,
            "wall_s": self.wall_s,
            "n_rows": self.n_rows,
            **self.extra,
        }


@dataclass
class AppMetrics:
    """Whole-run accumulation (reference: AppMetrics, OpSparkListener.scala:
    133-161).  ``start_time`` stays a wall-clock epoch (it anchors the
    mesh-event window across accumulators); DURATIONS are measured on
    ``time.perf_counter`` - the epoch clock can step under NTP and must
    never time a stage (the tests/test_style.py timing gate)."""

    stages: list[StageMetrics] = field(default_factory=list)
    start_time: float = field(default_factory=time.time)
    _pc_start: float = field(default_factory=time.perf_counter, repr=False)

    def __post_init__(self) -> None:
        # a metrics-registry view: every finite numeric leaf of
        # to_json() becomes a scrapeable series (weakref - a finished
        # run's metrics leave the scrape when the object does)
        _obs_metrics.metrics_registry().register_view("stage", self)

    def record(self, m: StageMetrics) -> None:
        self.stages.append(m)
        log.info(m.log_line())

    @contextlib.contextmanager
    def timed(self, stage, phase: str, n_rows: int) -> Iterator[None]:
        t0 = time.perf_counter()
        with _obs_trace.span(
            "stage." + phase, uid=stage.uid,
            op=stage.operation_name, rows=int(n_rows),
        ):
            try:
                yield
            finally:
                self.record(
                    StageMetrics(
                        stage_uid=stage.uid,
                        operation=stage.operation_name,
                        phase=phase,
                        wall_s=time.perf_counter() - t0,
                        n_rows=n_rows,
                    )
                )

    @property
    def total_wall_s(self) -> float:
        return time.perf_counter() - self._pc_start

    def by_operation(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for m in self.stages:
            out[m.operation] = out.get(m.operation, 0.0) + m.wall_s
        return dict(sorted(out.items(), key=lambda kv: -kv[1]))

    def to_json(self) -> dict:
        out = {
            "total_wall_s": self.total_wall_s,
            "stages": [m.to_json() for m in self.stages],
            "by_operation": self.by_operation(),
        }
        # degraded-mode events (collective stalls, straggler retries,
        # shrink-to-survivors recomputes) belong next to the stage walls
        # they inflated - scoped to THIS run's window so one model's
        # summary never reports another run's degradation
        ev = mesh_events(since_epoch=self.start_time)
        if ev:
            out["mesh_resilience_events"] = ev
        dropped = mesh_events_dropped()
        if dropped:
            # the feed failed at least once this process: say so next to
            # the (possibly empty) event list instead of letting a broken
            # feed read as a healthy mesh
            out["obs_events_dropped"] = dropped
        return out

    def snapshot(self) -> dict:
        """The metrics-registry view contract (the other telemetry
        classes call theirs ``snapshot`` too)."""
        return self.to_json()


@contextlib.contextmanager
def profile_to(path: Optional[str]) -> Iterator[None]:
    """Wrap a block in a JAX profiler trace (xplane dump readable by
    tensorboard/xprof) when ``path`` is set."""
    if not path:
        yield
        return
    import jax

    with jax.profiler.trace(path):
        yield
