"""User-facing DSL: rich operations on features.

Counterpart of the reference dsl package (reference: core/.../dsl/
RichFeaturesCollection.scala:69 transmogrify, RichNumericFeature.scala:479
sanityCheck + feature math, RichTextFeature pivot/tokenize).  Importing this
module patches operator methods onto Feature so user code reads like the
reference:

    family_size = sib_sp + par_ch + 1
    normed_age = age.fill_missing_with_mean().z_normalize()
    features = transmogrify([p_class, sex, age, ...])
    checked = survived.sanity_check(features, remove_bad_features=True)
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np

from .features.feature import Feature
from .ops.categorical import OneHotVectorizer
from .ops.scalers import FillMissingWithMean, OpScalarStandardScaler
from .ops.text import TextTokenizer
from .ops.transmogrifier import transmogrify
from .preparators.sanity_checker import SanityChecker
from .stages.base import LambdaTransformer
from .types.columns import Column, NumericColumn, TextColumn
from .types import feature_types as ft

Number = Union[int, float]


def _numeric_binary(op_name: str, fn) -> LambdaTransformer:
    def col_fn(a: Column, b: Column) -> Column:
        assert isinstance(a, NumericColumn) and isinstance(b, NumericColumn)
        # non-finite results (x/0, 0/0, inf-inf, overflow) become nulls
        # below, same as the reference's option-valued feature math —
        # silence the interim numpy warning rather than pay a pre-check pass
        with np.errstate(divide="ignore", invalid="ignore",
                         over="ignore", under="ignore"):
            vals = fn(a.values, b.values)
        mask = a.mask & b.mask
        ok = np.isfinite(vals)
        return NumericColumn(np.where(mask & ok, vals, 0.0), mask & ok, ft.Real)

    return LambdaTransformer(col_fn, ft.Real, operation_name=op_name)


def _numeric_unary(op_name: str, fn, out_type=ft.Real) -> LambdaTransformer:
    def col_fn(a: Column) -> Column:
        assert isinstance(a, NumericColumn)
        with np.errstate(divide="ignore", invalid="ignore",
                         over="ignore", under="ignore"):
            vals = fn(a.values)
        ok = np.isfinite(vals)
        return NumericColumn(np.where(a.mask & ok, vals, 0.0), a.mask & ok, out_type)

    return LambdaTransformer(col_fn, out_type, operation_name=op_name)


def _as_feature_op(self: Feature, other, op_name: str, fn, rev: bool = False):
    """feature-op-feature or feature-op-scalar arithmetic (reference:
    RichNumericFeature + - * /)."""
    if isinstance(other, Feature):
        stage = _numeric_binary(op_name, fn)
        return stage.set_input(self, other).get_output()
    k = float(other)
    scalar_fn = (lambda v: fn(np.full_like(v, k), v)) if rev else (lambda v: fn(v, k))
    stage = _numeric_unary(f"{op_name}_scalar", scalar_fn)
    return stage.set_input(self).get_output()


def _patch_feature() -> None:
    F = Feature
    F.__add__ = lambda s, o: _as_feature_op(s, o, "plus", np.add)
    F.__radd__ = lambda s, o: _as_feature_op(s, o, "plus", np.add, rev=True)
    F.__sub__ = lambda s, o: _as_feature_op(s, o, "minus", np.subtract)
    F.__rsub__ = lambda s, o: _as_feature_op(s, o, "minus", np.subtract, rev=True)
    F.__mul__ = lambda s, o: _as_feature_op(s, o, "times", np.multiply)
    F.__rmul__ = lambda s, o: _as_feature_op(s, o, "times", np.multiply, rev=True)
    F.__truediv__ = lambda s, o: _as_feature_op(s, o, "divide", np.divide)
    F.__rtruediv__ = lambda s, o: _as_feature_op(s, o, "divide", np.divide, rev=True)

    def fill_missing_with_mean(self: Feature, default: float = 0.0) -> Feature:
        return FillMissingWithMean(default=default).set_input(self).get_output()

    def z_normalize(self: Feature) -> Feature:
        return OpScalarStandardScaler().set_input(self).get_output()

    def pivot(self: Feature, top_k: int = 20, min_support: int = 10,
              track_nulls: bool = True) -> Feature:
        return (
            OneHotVectorizer(
                top_k=top_k, min_support=min_support, track_nulls=track_nulls
            )
            .set_input(self)
            .get_output()
        )

    def tokenize_f(self: Feature, **kw) -> Feature:
        return TextTokenizer(**kw).set_input(self).get_output()

    def sanity_check(
        self: Feature, features: Feature, remove_bad_features: bool = True, **kw
    ) -> Feature:
        checker = SanityChecker(remove_bad_features=remove_bad_features, **kw)
        return checker.set_input(self, features).get_output()

    def map_values(self: Feature, fn, output_type) -> Feature:
        """Row-function escape hatch (reference: FeatureLike.map) -
        vectorized over the host column values."""

        def col_fn(c: Column) -> Column:
            from .types.columns import column_from_list

            return column_from_list([fn(v) for v in c.to_list()], output_type)

        stage = LambdaTransformer(col_fn, output_type, operation_name="map")
        return stage.set_input(self).get_output()

    def alias(self: Feature, name: str) -> Feature:
        from .ops.combiner import AliasTransformer

        return AliasTransformer(name).set_input(self).get_output()

    # -- per-type .vectorize(...) (reference: Rich*Feature.vectorize) -------
    def vectorize(self: Feature, *, others: Sequence[Feature] = (),
                  **kw) -> Feature:
        """Type-dispatched default vectorizer for this feature (and
        optional same-type ``others`` sharing one stage), with the
        reference's per-type parameter surfaces (reference:
        RichNumericFeature.vectorize:325, RichTextFeature.vectorize:130,
        RichDateFeature/RichMapFeature/RichSetFeature/.vectorize)."""
        from .ops.categorical import OneHotVectorizer as _OneHot
        from .ops.dates import DateListVectorizer, DateVectorizer
        from .ops.geo import GeolocationVectorizer
        from .ops.maps import MapVectorizer
        from .ops.numeric import (
            BinaryVectorizer,
            IntegralVectorizer,
            RealNNVectorizer,
            RealVectorizer,
        )
        from .ops.text import SmartTextVectorizer, TextListHashingVectorizer

        t = self.ftype
        if issubclass(t, ft.OPMap):
            stage = MapVectorizer(**kw)
        elif issubclass(t, ft.Geolocation):
            stage = GeolocationVectorizer(**kw)
        elif issubclass(t, ft.DateList):  # before TextList (both OPList)
            stage = DateListVectorizer(**kw)
        elif issubclass(t, ft.Date):  # Date/DateTime (subtype of Integral)
            # reference parity: circular reps + SinceLast days
            # (RichDateFeature.vectorize:97-110)
            kw.setdefault("with_time_since", True)
            stage = DateVectorizer(**kw)
        elif issubclass(t, ft.Binary):
            stage = BinaryVectorizer(**kw)
        elif issubclass(t, ft.Integral):
            stage = IntegralVectorizer(**kw)
        elif issubclass(t, ft.RealNN):
            stage = RealNNVectorizer(**kw)
        elif issubclass(t, ft.Real):
            stage = RealVectorizer(**kw)
        elif issubclass(t, (ft.MultiPickList,)) or (
            issubclass(t, ft.Text) and t.is_categorical
        ):
            stage = _OneHot(**kw)
        elif issubclass(t, ft.TextList):
            stage = TextListHashingVectorizer(**kw)
        elif issubclass(t, ft.Text):
            stage = SmartTextVectorizer(**kw)
        elif issubclass(t, ft.OPVector):
            return self.combine(*others) if others else self
        else:
            raise TypeError(f"no default vectorizer for {t.__name__}")
        return stage.set_input(self, *others).get_output()

    def smart_vectorize(self: Feature, *, others: Sequence[Feature] = (),
                        **kw) -> Feature:
        """(reference: RichTextFeature.smartVectorize:214)"""
        from .ops.text import SmartTextVectorizer

        return SmartTextVectorizer(**kw).set_input(self, *others).get_output()

    # -- numeric enrichments (reference: RichNumericFeature) ----------------
    def bucketize(self: Feature, splits: Sequence[float],
                  track_nulls: bool = True) -> Feature:
        from .ops.bucketizers import NumericBucketizer

        return (
            NumericBucketizer(splits=list(splits), track_nulls=track_nulls)
            .set_input(self)
            .get_output()
        )

    def auto_bucketize(self: Feature, label: Feature, track_nulls: bool = True,
                       **kw) -> Feature:
        """(reference: RichNumericFeature.autoBucketize:298 - supervised
        decision-tree split points)"""
        from .ops.bucketizers import DecisionTreeNumericBucketizer

        return (
            DecisionTreeNumericBucketizer(track_nulls=track_nulls, **kw)
            .set_input(label, self)
            .get_output()
        )

    def scale(self: Feature, scaling_type: str = "linear", slope: float = 1.0,
              intercept: float = 0.0) -> Feature:
        from .ops.collections import ScalerTransformer

        return (
            ScalerTransformer(scaling_type=scaling_type, slope=slope,
                              intercept=intercept)
            .set_input(self)
            .get_output()
        )

    def descale(self: Feature, scaled_feature: Feature) -> Feature:
        """(reference: RichNumericFeature.descale:372 - reads the scaler
        args from the scaled feature's metadata).  Dispatches on the
        receiver's type: a Prediction routes to PredictionDescaler (the
        regression-on-scaled-label round trip, DescalerTransformer.
        scala:92) so ``prediction.descale(scaled_label)`` works the way
        users naturally write it."""
        from .ops.collections import DescalerTransformer, PredictionDescaler
        from .types.feature_types import Prediction

        stage = (
            PredictionDescaler()
            if issubclass(self.ftype, Prediction)
            else DescalerTransformer()
        )
        return stage.set_input(self, scaled_feature).get_output()

    def to_percentile(self: Feature, buckets: int = 100) -> Feature:
        from .ops.scalers import PercentileCalibrator

        return (
            PercentileCalibrator(buckets=buckets).set_input(self).get_output()
        )

    def to_isotonic_calibrated(self: Feature, label: Feature,
                               is_isotonic: bool = True) -> Feature:
        from .ops.collections import IsotonicRegressionCalibrator

        return (
            IsotonicRegressionCalibrator(isotonic=is_isotonic)
            .set_input(label, self)
            .get_output()
        )

    # -- text enrichments (reference: RichTextFeature) ----------------------
    def indexed(self: Feature) -> Feature:
        from .ops.categorical import StringIndexer

        return StringIndexer().set_input(self).get_output()

    def deindexed(self: Feature, labels: Sequence[str]) -> Feature:
        from .ops.categorical import IndexToString

        return IndexToString(labels=list(labels)).set_input(self).get_output()

    def to_ngram_similarity(self: Feature, that: Feature,
                            n_gram_size: int = 3) -> Feature:
        from .ops.text_analysis import NGramSimilarity

        return (
            NGramSimilarity(n=n_gram_size).set_input(self, that).get_output()
        )

    def detect_languages(self: Feature) -> Feature:
        from .ops.text_analysis import LangDetector

        return LangDetector().set_input(self).get_output()

    def recognize_entities(self: Feature) -> Feature:
        from .ops.text_analysis import NameEntityRecognizer

        return NameEntityRecognizer().set_input(self).get_output()

    def text_len(self: Feature) -> Feature:
        from .ops.text_analysis import TextLenTransformer

        return TextLenTransformer().set_input(self).get_output()

    def to_email_domain(self: Feature) -> Feature:
        from .ops.text_analysis import EmailToPickList

        return EmailToPickList().set_input(self).get_output()

    def to_email_prefix(self: Feature) -> Feature:
        return map_values(
            self,
            lambda v: (v.split("@", 1)[0] if v and "@" in v else None),
            ft.Text,
        )

    def to_domain(self: Feature) -> Feature:
        from .ops.text_analysis import UrlToDomain

        return UrlToDomain().set_input(self).get_output()

    def to_protocol(self: Feature) -> Feature:
        return map_values(
            self,
            lambda v: (v.split("://", 1)[0].lower()
                       if v and "://" in v else None),
            ft.Text,
        )

    def is_valid_url(self: Feature) -> Feature:
        import re as _re

        url_re = _re.compile(r"^(https?|ftp)://[^/\s:]+", _re.IGNORECASE)
        return map_values(
            self,
            lambda v: None if v is None else bool(url_re.match(v)),
            ft.Binary,
        )

    def is_valid_phone(self: Feature, region: str = "US") -> Feature:
        from .ops.text_analysis import PhoneNumberParser

        return PhoneNumberParser(region=region).set_input(self).get_output()

    def detect_mime_types(self: Feature) -> Feature:
        from .ops.text_analysis import MimeTypeDetector

        return MimeTypeDetector().set_input(self).get_output()

    # -- set/list/vector/map enrichments ------------------------------------
    def jaccard_similarity(self: Feature, that: Feature) -> Feature:
        from .ops.text_analysis import JaccardSimilarity

        return JaccardSimilarity().set_input(self, that).get_output()

    def combine(self: Feature, *others: Feature) -> Feature:
        """(reference: RichVectorFeature.combine)"""
        from .ops.combiner import VectorsCombiner

        return VectorsCombiner().set_input(self, *others).get_output()

    def drop_indices_by(self: Feature, predicate) -> Feature:
        from .ops.combiner import DropIndicesByTransformer

        return (
            DropIndicesByTransformer(predicate).set_input(self).get_output()
        )

    def filter_map(self: Feature, allow_keys=None, block_keys=(),
                   clean_keys: bool = True) -> Feature:
        from .ops.collections import FilterMap

        return (
            FilterMap(allow_keys=allow_keys, block_keys=block_keys,
                      clean_keys=clean_keys)
            .set_input(self)
            .get_output()
        )

    def to_occur(self: Feature, matches=None) -> Feature:
        from .ops.collections import ToOccurTransformer

        return ToOccurTransformer(matches=matches).set_input(self).get_output()

    # -- text-ML sugar (reference RichTextFeature tf/idf/tfidf, countVec,
    # lda, word2vec, removeStopWords, tokenizeRegex) ------------------------
    def tf(self: Feature, num_features: int = 512) -> Feature:
        """Hashing term frequencies of a TextList -> OPVector
        (reference: RichTextFeature.tf via HashingTF)."""
        from .ops.text import TextListHashingVectorizer

        return (
            TextListHashingVectorizer(hash_dims=num_features)
            .set_input(self).get_output()
        )

    def idf(self: Feature, min_doc_freq: int = 0) -> Feature:
        """Inverse document frequency scaling of a TF vector
        (reference: RichTextFeature.idf via ml.feature.IDF)."""
        from .ops.text import OpIDF

        return OpIDF(min_doc_freq=min_doc_freq).set_input(self).get_output()

    def tfidf(self: Feature, num_features: int = 512,
              min_doc_freq: int = 0) -> Feature:
        """tf then idf (reference: RichTextFeature.tfidf)."""
        return idf(tf(self, num_features), min_doc_freq)

    def count_vec(self: Feature, vocab_size: int = 1 << 18,
                  min_df: float = 1.0, min_tf: float = 1.0,
                  binary: bool = False) -> Feature:
        """Vocabulary term counts (reference: RichTextFeature.countVec)."""
        from .ops.text import OpCountVectorizer

        return (
            OpCountVectorizer(vocab_size=vocab_size, min_df=min_df,
                              min_tf=min_tf, binary=binary)
            .set_input(self).get_output()
        )

    def lda(self: Feature, k: int = 10, max_iter: int = 30) -> Feature:
        """Topic mixture of a term-count/TF vector (reference:
        RichVectorFeature.lda via ml.clustering.LDA)."""
        from .models.unsupervised import OpLDA

        return OpLDA(k=k, max_iter=max_iter).set_input(self).get_output()

    def word2vec(self: Feature, vector_size: int = 100,
                 min_count: int = 5) -> Feature:
        """Mean skip-gram embedding of a TextList (reference:
        RichTextFeature.word via ml.feature.Word2Vec)."""
        from .models.unsupervised import OpWord2Vec

        return (
            OpWord2Vec(vector_size=vector_size, min_count=min_count)
            .set_input(self).get_output()
        )

    def remove_stop_words(self: Feature, language: str = "en") -> Feature:
        """Drop function words from a TextList (reference:
        RichTextFeature.removeStopWords via StopWordsRemover)."""
        from .ops.stopwords import stopwords_for
        from .types.feature_types import TextList as _TL

        stops = stopwords_for(language)
        return map_values(
            self,
            lambda toks: tuple(t for t in (toks or ()) if t not in stops),
            _TL,
        )

    def tokenize_regex(self: Feature, pattern: str,
                       to_lowercase: bool = True) -> Feature:
        """Split on a regex (reference: RichTextFeature.tokenizeRegex)."""
        import re as _re

        from .types.feature_types import TextList as _TL

        rx = _re.compile(pattern)

        def _split(v):
            if not v:
                return ()
            toks = [t for t in rx.split(v) if t]
            return tuple(t.lower() for t in toks) if to_lowercase else tuple(toks)

        return map_values(self, _split, _TL)

    # -- row-level functional sugar (reference FeatureLike exists/filter/
    # replaceWith - Option-typed row ops become masked column maps) ---------
    def exists(self: Feature, fn) -> Feature:
        """True where the (non-missing) value satisfies ``fn``
        (reference: RichFeature.exists)."""
        from .types.feature_types import Binary as _B

        return map_values(
            self, lambda v: bool(v is not None and fn(v)), _B
        )

    def replace_with(self: Feature, old, new) -> Feature:
        """Substitute one value for another (reference:
        RichFeature.replaceWith)."""
        return map_values(
            self, lambda v, _o=old, _n=new: _n if v == _o else v, self.ftype
        )

    def filter_values(self: Feature, fn, default=None) -> Feature:
        """Keep values satisfying ``fn``, else ``default`` (reference:
        RichFeature.filter/filterNot)."""
        return map_values(
            self,
            lambda v: v if (v is not None and fn(v)) else default,
            self.ftype,
        )

    def parse_phone(self: Feature, region: str = "US") -> Feature:
        """Normalize a phone number to digits-with-country-code, None when
        invalid (reference: RichPhoneFeature.parsePhone via
        libphonenumber)."""
        from .ops.text_analysis import parse_phone as _pp
        from .types.feature_types import Phone as _P

        return map_values(self, lambda v: _pp(v, region), _P)

    def to_date_list(self: Feature) -> Feature:
        """Wrap a Date in a single-element DateList (reference:
        RichDateFeature.toDateList:54)."""
        from .types.feature_types import DateList as _DL

        return map_values(
            self, lambda v: () if v is None else (v,), _DL
        )

    def to_multi_pick_list(self: Feature) -> Feature:
        """Set-valued MultiPickList: a scalar Text becomes its 0/1-element
        set (the reference receiver, RichTextFeature.toMultiPickList:58);
        a TextList becomes its distinct-token set.  Strings must NOT be
        iterated - frozenset('red') would char-split silently."""
        from .types.feature_types import MultiPickList as _MPL

        def _to_set(v):
            if v is None:
                return frozenset()
            if isinstance(v, str):
                return frozenset((v,))
            return frozenset(v)

        return map_values(self, _to_set, _MPL)

    def to_unit_circle(self: Feature, period: str = "HourOfDay") -> Feature:
        """(sin, cos) encoding of a date's position in ``period``
        (reference: RichDateFeature.toUnitCircle via
        DateToUnitCircleTransformer)."""
        from .ops.dates import DateVectorizer

        return DateVectorizer(periods=(period,), track_nulls=False) \
            .set_input(self).get_output()

    F.fill_missing_with_mean = fill_missing_with_mean
    F.z_normalize = z_normalize
    F.pivot = pivot
    F.tokenize = tokenize_f
    F.sanity_check = sanity_check
    F.map_values = map_values
    F.vectorize = vectorize
    F.smart_vectorize = smart_vectorize
    F.alias = alias
    F.bucketize = bucketize
    F.auto_bucketize = auto_bucketize
    F.scale = scale
    F.descale = descale
    F.to_percentile = to_percentile
    F.to_isotonic_calibrated = to_isotonic_calibrated
    F.indexed = indexed
    F.deindexed = deindexed
    F.to_ngram_similarity = to_ngram_similarity
    F.detect_languages = detect_languages
    F.recognize_entities = recognize_entities
    F.text_len = text_len
    F.to_email_domain = to_email_domain
    F.to_email_prefix = to_email_prefix
    F.to_domain = to_domain
    F.to_protocol = to_protocol
    F.is_valid_url = is_valid_url
    F.is_valid_phone = is_valid_phone
    F.detect_mime_types = detect_mime_types
    F.jaccard_similarity = jaccard_similarity
    F.combine = combine
    F.drop_indices_by = drop_indices_by
    F.filter_map = filter_map
    F.to_occur = to_occur
    F.tf = tf
    F.idf = idf
    F.tfidf = tfidf
    F.count_vec = count_vec
    F.lda = lda
    F.word2vec = word2vec
    F.remove_stop_words = remove_stop_words
    F.tokenize_regex = tokenize_regex
    F.exists = exists
    F.replace_with = replace_with
    F.filter_values = filter_values
    F.parse_phone = parse_phone
    F.to_unit_circle = to_unit_circle
    F.to_date_list = to_date_list
    F.to_multi_pick_list = to_multi_pick_list


_patch_feature()

__all__ = ["transmogrify"]
