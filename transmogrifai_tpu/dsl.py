"""User-facing DSL: rich operations on features.

Counterpart of the reference dsl package (reference: core/.../dsl/
RichFeaturesCollection.scala:69 transmogrify, RichNumericFeature.scala:479
sanityCheck + feature math, RichTextFeature pivot/tokenize).  Importing this
module patches operator methods onto Feature so user code reads like the
reference:

    family_size = sib_sp + par_ch + 1
    normed_age = age.fill_missing_with_mean().z_normalize()
    features = transmogrify([p_class, sex, age, ...])
    checked = survived.sanity_check(features, remove_bad_features=True)
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np

from .features.feature import Feature
from .ops.categorical import OneHotVectorizer
from .ops.scalers import FillMissingWithMean, OpScalarStandardScaler
from .ops.text import TextTokenizer
from .ops.transmogrifier import transmogrify
from .preparators.sanity_checker import SanityChecker
from .stages.base import LambdaTransformer
from .types.columns import Column, NumericColumn, TextColumn
from .types import feature_types as ft

Number = Union[int, float]


def _numeric_binary(op_name: str, fn) -> LambdaTransformer:
    def col_fn(a: Column, b: Column) -> Column:
        assert isinstance(a, NumericColumn) and isinstance(b, NumericColumn)
        vals = fn(a.values, b.values)
        mask = a.mask & b.mask
        ok = np.isfinite(vals)
        return NumericColumn(np.where(mask & ok, vals, 0.0), mask & ok, ft.Real)

    return LambdaTransformer(col_fn, ft.Real, operation_name=op_name)


def _numeric_unary(op_name: str, fn, out_type=ft.Real) -> LambdaTransformer:
    def col_fn(a: Column) -> Column:
        assert isinstance(a, NumericColumn)
        vals = fn(a.values)
        ok = np.isfinite(vals)
        return NumericColumn(np.where(a.mask & ok, vals, 0.0), a.mask & ok, out_type)

    return LambdaTransformer(col_fn, out_type, operation_name=op_name)


def _as_feature_op(self: Feature, other, op_name: str, fn, rev: bool = False):
    """feature-op-feature or feature-op-scalar arithmetic (reference:
    RichNumericFeature + - * /)."""
    if isinstance(other, Feature):
        stage = _numeric_binary(op_name, fn)
        return stage.set_input(self, other).get_output()
    k = float(other)
    scalar_fn = (lambda v: fn(np.full_like(v, k), v)) if rev else (lambda v: fn(v, k))
    stage = _numeric_unary(f"{op_name}_scalar", scalar_fn)
    return stage.set_input(self).get_output()


def _patch_feature() -> None:
    F = Feature
    F.__add__ = lambda s, o: _as_feature_op(s, o, "plus", np.add)
    F.__radd__ = lambda s, o: _as_feature_op(s, o, "plus", np.add, rev=True)
    F.__sub__ = lambda s, o: _as_feature_op(s, o, "minus", np.subtract)
    F.__rsub__ = lambda s, o: _as_feature_op(s, o, "minus", np.subtract, rev=True)
    F.__mul__ = lambda s, o: _as_feature_op(s, o, "times", np.multiply)
    F.__rmul__ = lambda s, o: _as_feature_op(s, o, "times", np.multiply, rev=True)
    F.__truediv__ = lambda s, o: _as_feature_op(s, o, "divide", np.divide)
    F.__rtruediv__ = lambda s, o: _as_feature_op(s, o, "divide", np.divide, rev=True)

    def fill_missing_with_mean(self: Feature, default: float = 0.0) -> Feature:
        return FillMissingWithMean(default=default).set_input(self).get_output()

    def z_normalize(self: Feature) -> Feature:
        return OpScalarStandardScaler().set_input(self).get_output()

    def pivot(self: Feature, top_k: int = 20, min_support: int = 10,
              track_nulls: bool = True) -> Feature:
        return (
            OneHotVectorizer(
                top_k=top_k, min_support=min_support, track_nulls=track_nulls
            )
            .set_input(self)
            .get_output()
        )

    def tokenize_f(self: Feature, **kw) -> Feature:
        return TextTokenizer(**kw).set_input(self).get_output()

    def sanity_check(
        self: Feature, features: Feature, remove_bad_features: bool = True, **kw
    ) -> Feature:
        checker = SanityChecker(remove_bad_features=remove_bad_features, **kw)
        return checker.set_input(self, features).get_output()

    def map_values(self: Feature, fn, output_type) -> Feature:
        """Row-function escape hatch (reference: FeatureLike.map) -
        vectorized over the host column values."""

        def col_fn(c: Column) -> Column:
            from .types.columns import column_from_list

            return column_from_list([fn(v) for v in c.to_list()], output_type)

        stage = LambdaTransformer(col_fn, output_type, operation_name="map")
        return stage.set_input(self).get_output()

    def vectorize_defaults(self: Feature, **kw) -> Feature:
        return transmogrify([self])

    def alias(self: Feature, name: str) -> Feature:
        from .ops.combiner import AliasTransformer

        return AliasTransformer(name).set_input(self).get_output()

    F.fill_missing_with_mean = fill_missing_with_mean
    F.z_normalize = z_normalize
    F.pivot = pivot
    F.tokenize = tokenize_f
    F.sanity_check = sanity_check
    F.map_values = map_values
    F.vectorize = vectorize_defaults
    F.alias = alias


_patch_feature()

__all__ = ["transmogrify"]
